"""Overload & failure resilience plane (PR 5): end-to-end deadlines,
admission control / load shedding, the device-path circuit breaker with
host-oracle degradation, client retry/backoff, the fault-injection
harness, and tri-plane (REST/gRPC/aio) typed-error parity."""

import json
import threading
import time
import urllib.error
import urllib.request

import grpc
import pytest

from keto_tpu import faults
from keto_tpu.api import ReadClient, RetryPolicy
from keto_tpu.api.batcher import CheckBatcher
from keto_tpu.api.daemon import Daemon
from keto_tpu.config import Config, ConfigError
from keto_tpu.engine.definitions import RESULT_IS_MEMBER, Membership
from keto_tpu.errors import (
    CheckBatchFailedError,
    DeadlineExceededError,
    KetoError,
    OverloadedError,
)
from keto_tpu.ketoapi import RelationTuple
from keto_tpu.namespace import Namespace
from keto_tpu.observability import Metrics, RequestTrace
from keto_tpu.registry import Registry
from keto_tpu.resilience import (
    CircuitBreaker,
    Deadline,
    backoff_delays,
    ingest_deadline,
    parse_timeout_ms,
    retry_after_header_value,
)

NS = [Namespace(name="files"), Namespace(name="groups")]


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# unit: Deadline / ingestion
# ---------------------------------------------------------------------------


class TestDeadlineUnit:
    def test_budget_and_expiry(self):
        dl = Deadline.after_ms(50)
        assert not dl.expired()
        assert 0 < dl.remaining_s() <= 0.05
        time.sleep(0.06)
        assert dl.expired()
        assert dl.remaining_s() == 0.0

    def test_parse_timeout_ms(self):
        assert parse_timeout_ms(None) is None
        assert parse_timeout_ms("") is None
        assert parse_timeout_ms("250") == 250.0
        from keto_tpu.errors import MalformedInputError

        with pytest.raises(MalformedInputError):
            parse_timeout_ms("soon")
        with pytest.raises(MalformedInputError):
            parse_timeout_ms("-5")

    def test_precedence_and_clamp(self):
        cfg = Config({"serve": {"check": {
            "default_deadline_ms": 1000, "max_deadline_ms": 2000,
        }}})
        # explicit request budget wins over the default
        assert ingest_deadline(cfg, request_ms=100).budget_s == pytest.approx(0.1)
        # native gRPC deadline used when no header
        assert ingest_deadline(cfg, native_s=0.5).budget_s == pytest.approx(0.5)
        # default applies when neither
        assert ingest_deadline(cfg).budget_s == pytest.approx(1.0)
        # max clamps everything
        assert ingest_deadline(cfg, request_ms=60000).budget_s == pytest.approx(2.0)

    def test_no_config_no_deadline_and_sentinel_native(self):
        cfg = Config({})
        assert ingest_deadline(cfg) is None
        # grpc's "no deadline" sentinel-huge time_remaining is NOT a budget
        assert ingest_deadline(cfg, native_s=1e15) is None

    def test_expired_native_deadline_is_expired_not_absent(self):
        # a client deadline that expired in transit must 504 at
        # admission, not silently become "no deadline"
        dl = ingest_deadline(Config({}), native_s=-0.01)
        assert dl is not None and dl.expired()

    def test_retry_after_header_value(self):
        assert retry_after_header_value(None) == "1"
        assert retry_after_header_value(0.05) == "1"
        assert retry_after_header_value(3.2) == "4"


# ---------------------------------------------------------------------------
# unit: faults
# ---------------------------------------------------------------------------


class TestFaultsUnit:
    def test_configure_parses_all_modes(self):
        faults.configure(
            "device_launch=stall:0.01, store_read=error:boom, batch_corrupt=on"
        )
        assert faults.get("device_launch").stall_s == 0.01
        assert faults.get("store_read").error == "boom"
        assert faults.get("batch_corrupt") is not None
        faults.clear("store_read")
        assert faults.get("store_read") is None
        faults.clear()
        assert faults.get("device_launch") is None

    def test_inject_stall_and_error(self):
        faults.set_fault("device_launch", stall_s=0.03)
        t0 = time.perf_counter()
        faults.inject("device_launch")
        assert time.perf_counter() - t0 >= 0.03
        assert faults.get("device_launch").hits == 1
        faults.set_fault("store_read", error="disk gone")
        with pytest.raises(faults.FaultInjected, match="disk gone"):
            faults.inject("store_read")

    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError):
            faults.set_fault("warp_core")
        with pytest.raises(ValueError):
            faults.configure("device_launch=explode:1")

    def test_disarmed_inject_is_noop(self):
        faults.inject("device_launch")  # no spec: returns silently

    def test_configure_parses_crash_and_suffixes(self):
        faults.configure(
            "store_commit_pre=crash:137@0.25,"
            "changelog_append=crash:9!1,"
            "device_launch=stall:0.5@0.2!3,"
            "watch_broadcast=crash:",
        )
        pre = faults.get("store_commit_pre")
        assert pre.crash == 137 and pre.probability == 0.25
        cl = faults.get("changelog_append")
        assert cl.crash == 9 and cl.max_hits == 1
        dl = faults.get("device_launch")
        assert dl.stall_s == 0.5 and dl.probability == 0.2 and dl.max_hits == 3
        assert faults.get("watch_broadcast").crash == 137  # default code
        faults.clear()
        # value-less modes carry suffixes on the mode token itself
        faults.configure("mirror_corrupt=on!1@0.5")
        mc = faults.get("mirror_corrupt")
        assert mc.max_hits == 1 and mc.probability == 0.5
        assert mc.crash is None and mc.error is None and mc.stall_s == 0
        faults.clear()

    def test_error_messages_taken_verbatim(self):
        # '@'/'!' are legitimate message content — never reinterpreted
        # as probability/max_hits suffixes on the error mode
        faults.configure("store_read=error:HTTP 429!3")
        spec = faults.get("store_read")
        assert spec.error == "HTTP 429!3"
        assert spec.max_hits is None and spec.probability == 1.0
        faults.clear()

    def test_crash_inject_exits_process(self, tmp_path):
        """The crash mode really is os._exit at the point — proven in a
        subprocess (faults.py imports stand alone, so the child pays no
        jax/grpc import)."""
        import subprocess
        import sys

        code = (
            "import importlib.util\n"
            "spec = importlib.util.spec_from_file_location("
            "'faults', 'keto_tpu/faults.py')\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "m.configure('store_commit_pre=crash:41')\n"
            "m.inject('store_commit_pre')\n"
            "raise SystemExit(0)  # unreachable: inject never returns\n"
        )
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, "-c", code], cwd=repo, timeout=60
        )
        assert proc.returncode == 41

    def test_crash_spec_respects_max_hits_before_firing(self):
        """A crash spec whose max_hits budget is exhausted passes through
        (exercised in-process: should_fire consumes the only hit, the
        next inject is a no-op — were it not, this test would die)."""
        spec = faults.set_fault("store_commit_pre", crash=137, max_hits=0)
        faults.inject("store_commit_pre")  # budget 0: must NOT exit
        assert spec.hits == 0
        faults.clear()


# ---------------------------------------------------------------------------
# unit: backoff + RetryPolicy
# ---------------------------------------------------------------------------


class _FakeRpcError(grpc.RpcError):
    def __init__(self, name):
        self._name = name

    def code(self):
        class _C:
            pass

        c = _C()
        c.name = self._name
        return c


class TestBackoffAndRetry:
    def test_full_jitter_bounded_by_cap(self):
        import random

        delays = backoff_delays(base_s=0.1, cap_s=0.4, rng=random.Random(7))
        seen = [next(delays) for _ in range(20)]
        assert all(0 <= d <= 0.4 for d in seen)

    def test_retries_then_succeeds(self):
        sleeps = []
        pol = RetryPolicy(max_attempts=4, base_s=0.01, sleep=sleeps.append)
        calls = []

        def fn(remaining):
            calls.append(remaining)
            if len(calls) < 3:
                raise _FakeRpcError("UNAVAILABLE")
            return "ok"

        assert pol.call(fn) == "ok"
        assert len(calls) == 3
        assert pol.stats["retries"] == 2
        assert len(sleeps) == 2

    def test_non_retryable_raises_immediately(self):
        pol = RetryPolicy(max_attempts=4, sleep=lambda s: None)
        with pytest.raises(_FakeRpcError):
            pol.call(lambda r: (_ for _ in ()).throw(
                _FakeRpcError("INVALID_ARGUMENT")
            ))
        assert pol.stats["retries"] == 0

    def test_budget_aware_giveup(self):
        import random

        # base delay far larger than the remaining budget: the policy
        # must re-raise instead of sleeping past the deadline
        slept = []
        pol = RetryPolicy(
            max_attempts=5, base_s=10.0, cap_s=10.0,
            sleep=slept.append, rng=random.Random(1),
        )
        with pytest.raises(_FakeRpcError):
            pol.call(
                lambda r: (_ for _ in ()).throw(_FakeRpcError("UNAVAILABLE")),
                budget_s=0.05,
            )
        assert not slept
        assert pol.stats["giveups"] == 1

    def test_counter_wired(self):
        m = Metrics()
        pol = RetryPolicy(
            max_attempts=2, base_s=0.0, counter=m.client_retries_total,
            sleep=lambda s: None,
        )
        calls = []

        def fn(remaining):
            calls.append(1)
            if len(calls) < 2:
                raise _FakeRpcError("RESOURCE_EXHAUSTED")
            return "ok"

        assert pol.call(fn) == "ok"
        assert m.client_retries_total._value.get() == 1

    def test_read_client_wires_policy_write_client_never(self):
        from keto_tpu.api.client import WriteClient

        ch = grpc.insecure_channel("127.0.0.1:1")  # never dialed
        rc = ReadClient(ch, retry_policy=RetryPolicy())
        wc = WriteClient(ch)
        assert rc._retry is not None
        assert wc._retry is None
        ch.close()


class _FlakyChannel:
    """Fake grpc.Channel: every unary_unary callable raises UNAVAILABLE
    `fail_times` times, then answers with the right response message for
    its service path. Counts attempts per path so the retry contract on
    every read surface is assertable without a server."""

    def __init__(self, fail_times: int, code: str = "UNAVAILABLE"):
        self.fail_times = fail_times
        self.code = code
        self.attempts: dict[str, int] = {}

    def _response_for(self, path: str):
        from keto_tpu.api.descriptors import pb

        if path.endswith("/Check"):
            return pb.CheckResponse(allowed=True, snaptoken="tok")
        if path.endswith("/Filter"):
            r = pb.FilterResponse(snaptoken="tok")
            r.allowed_objects.extend(["doc"])
            return r
        if path.endswith("/ListObjects"):
            r = pb.ListObjectsResponse(snaptoken="tok")
            r.objects.extend(["doc"])
            return r
        if path.endswith("/ListSubjects"):
            r = pb.ListSubjectsResponse(snaptoken="tok")
            r.subject_ids.extend(["alice"])
            return r
        if path.endswith("/TransactRelationTuples"):
            return pb.TransactRelationTuplesResponse()
        raise AssertionError(f"unexpected path {path}")

    def unary_unary(self, path, request_serializer=None,
                    response_deserializer=None):
        def call(req, timeout=None, metadata=None):
            n = self.attempts.get(path, 0) + 1
            self.attempts[path] = n
            if n <= self.fail_times:
                raise _FakeRpcError(self.code)
            return self._response_for(path)

        return call

    def close(self):
        pass


class TestRetryOnNewerReadSurfaces:
    """Satellite: RetryPolicy fires on UNAVAILABLE for the post-PR-5
    read surfaces — filter, list_objects, list_subjects, check_explain
    (everything riding ReadClient._rpc) — and NEVER for writes."""

    def _client(self, fail_times=2):
        ch = _FlakyChannel(fail_times)
        pol = RetryPolicy(max_attempts=4, base_s=0.0, sleep=lambda s: None)
        return ReadClient(ch, retry_policy=pol), ch, pol

    def test_filter_retries_unavailable(self):
        rc, ch, pol = self._client()
        allowed, tok = rc.filter("files", "owner", "alice", ["doc", "x"])
        assert allowed == ["doc"] and tok == "tok"
        assert ch.attempts[f"/{_svc('FILTER_SERVICE')}/Filter"] == 3
        assert pol.stats["retries"] == 2

    def test_list_objects_retries_unavailable(self):
        rc, ch, pol = self._client()
        objs, _next, tok = rc.list_objects("files", "owner", "alice")
        assert objs == ["doc"] and tok == "tok"
        assert pol.stats["retries"] == 2

    def test_list_subjects_retries_unavailable(self):
        rc, ch, pol = self._client()
        subs, _next, tok = rc.list_subjects("files", "doc", "owner")
        assert subs == ["alice"] and tok == "tok"
        assert pol.stats["retries"] == 2

    def test_check_explain_retries_unavailable(self):
        rc, ch, pol = self._client()
        out = rc.check_explain(t("files:doc#owner@alice"))
        assert out.allowed is True and out.snaptoken == "tok"
        assert out.decision_trace is None  # fake answers carry no trace
        assert pol.stats["retries"] == 2

    def test_exhausted_attempts_reraise(self):
        rc, ch, pol = self._client(fail_times=99)
        with pytest.raises(_FakeRpcError):
            rc.filter("files", "owner", "alice", ["doc"])
        assert pol.stats["attempts"] == 4  # max_attempts, then re-raise

    def test_writes_never_retry(self):
        from keto_tpu.api.client import WriteClient

        ch = _FlakyChannel(fail_times=99)
        wc = WriteClient(ch)
        with pytest.raises(_FakeRpcError):
            wc.transact(insert=[t("files:doc#owner@alice")])
        # exactly ONE attempt: a retried transact could double-apply
        assert sum(ch.attempts.values()) == 1


def _svc(name: str) -> str:
    import keto_tpu.api.descriptors as _d

    return getattr(_d, name)


# ---------------------------------------------------------------------------
# unit: CircuitBreaker
# ---------------------------------------------------------------------------


class TestCircuitBreakerUnit:
    def test_full_cycle(self):
        clock = [0.0]
        br = CircuitBreaker(threshold=2, cooldown_s=5.0, clock=lambda: clock[0])
        assert br.allow() and br.state == "closed"
        br.record_failure()
        assert br.state == "closed"  # one short of the threshold
        br.record_failure()
        assert br.state == "open"
        assert not br.allow()  # cooling down
        clock[0] = 5.1
        assert br.allow()  # the half-open probe
        assert br.state == "half_open"
        assert not br.allow()  # only ONE probe at a time
        br.record_success()
        assert br.state == "closed"
        assert list(br.transitions) == ["open", "half_open", "closed"]

    def test_half_open_failure_reopens(self):
        clock = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=2.0, clock=lambda: clock[0])
        br.record_failure()
        assert br.state == "open"
        clock[0] = 2.1
        assert br.allow()
        br.record_failure()
        assert br.state == "open"
        assert not br.allow()  # new cooldown started
        clock[0] = 4.2
        assert br.allow()

    def test_lost_probe_reclaimed_after_cooldown(self):
        # a probe group that never reports an outcome (riders expired at
        # the launch boundary, engine failed pre-device) must not wedge
        # the breaker half-open forever: after one cooldown the probe
        # slot is reclaimed
        clock = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=2.0, clock=lambda: clock[0])
        br.record_failure()
        clock[0] = 2.1
        assert br.allow()  # probe granted... and then lost
        assert not br.allow()
        clock[0] = 4.2  # a cooldown later: reclaimed
        assert br.allow()
        br.record_success()
        assert br.state == "closed"

    def test_success_resets_failure_streak(self):
        br = CircuitBreaker(threshold=2)
        br.record_failure()
        br.record_success()
        br.record_failure()
        assert br.state == "closed"  # streak broken: 2 consecutive needed

    def test_metrics_gauge_and_transitions(self):
        m = Metrics()
        clock = [0.0]
        br = CircuitBreaker(
            threshold=1, cooldown_s=1.0, metrics=m, clock=lambda: clock[0]
        )
        br.record_failure()
        assert m.breaker_state._value.get() == 1
        clock[0] = 1.1
        br.allow()
        assert m.breaker_state._value.get() == 2
        br.record_success()
        assert m.breaker_state._value.get() == 0

    def test_trip_holds_against_inflight_successes(self):
        """A scrubber trip() must not be undone by record_success from
        batches already in flight when the trip landed: their outcome
        says nothing about the out-of-band evidence (mirror divergence)
        that opened the breaker."""
        clock = [0.0]
        br = CircuitBreaker(threshold=5, cooldown_s=5.0, clock=lambda: clock[0])
        br.trip()
        assert br.state == "open"
        br.record_success()  # straggler from a pre-trip batch
        assert br.state == "open"
        assert not br.allow()  # still cooling down
        clock[0] = 5.1
        assert br.allow()  # half-open probe granted after the floor
        assert br.state == "half_open"
        br.record_success()  # the probe's own outcome closes it
        assert br.state == "closed"

    def test_trip_custom_cooldown(self):
        clock = [0.0]
        br = CircuitBreaker(threshold=5, cooldown_s=5.0, clock=lambda: clock[0])
        br.trip(cooldown_s=1.0)
        clock[0] = 0.5
        assert not br.allow()
        clock[0] = 1.1
        assert br.allow() and br.state == "half_open"


# ---------------------------------------------------------------------------
# batcher resilience (threaded plane; the aio twin is covered through the
# tri-plane daemon below)
# ---------------------------------------------------------------------------


class _GatedEngine:
    def __init__(self):
        self.gate = threading.Event()
        self.batches = []

    def check_batch(self, tuples, max_depth=0):
        self.batches.append(list(tuples))
        assert self.gate.wait(timeout=30)
        return [RESULT_IS_MEMBER for _ in tuples]


class TestBatcherAdmission:
    def test_admission_bound_is_atomic(self):
        eng = _GatedEngine()
        b = CheckBatcher(eng, window_s=0.0, max_queue=1)
        try:
            res = {}
            th = threading.Thread(
                target=lambda: res.update(ok=b.check(t("files:x#owner@u"))),
                daemon=True,
            )
            th.start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and b._pending < 1:
                time.sleep(0.002)
            assert b._pending == 1
            # the bound holds at admit() AND at enqueue
            with pytest.raises(OverloadedError):
                b.admit()
            with pytest.raises(OverloadedError) as ei:
                b.check(t("files:y#owner@u"))
            assert ei.value.status == 429
            assert ei.value.retry_after_s > 0
            eng.gate.set()
            th.join(timeout=10)
            assert res["ok"] is RESULT_IS_MEMBER
            # slot released: admission open again
            b.admit()
        finally:
            eng.gate.set()
            b.close()

    def test_shed_counter_increments(self):
        m = Metrics()
        eng = _GatedEngine()
        b = CheckBatcher(eng, window_s=0.0, max_queue=1, metrics=m)
        try:
            th = threading.Thread(
                target=lambda: b.check(t("files:x#owner@u")), daemon=True
            )
            th.start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and b._pending < 1:
                time.sleep(0.002)
            with pytest.raises(OverloadedError):
                b.check(t("files:y#owner@u"))
            assert (
                m.requests_shed_total.labels("queue_full")._value.get() >= 1
            )
            eng.gate.set()
            th.join(timeout=10)
        finally:
            eng.gate.set()
            b.close()


class TestBatcherDeadline:
    def test_caller_fails_fast_on_gated_engine(self):
        m = Metrics()
        eng = _GatedEngine()
        b = CheckBatcher(eng, window_s=0.0, metrics=m)
        try:
            rt = RequestTrace(deadline=Deadline(0.08))
            t0 = time.perf_counter()
            with pytest.raises(DeadlineExceededError) as ei:
                b.check(t("files:x#owner@u"), rt=rt)
            elapsed = time.perf_counter() - t0
            assert elapsed < 2 * 0.08 + 0.25  # fails at ~1x the budget
            assert ei.value.status == 504
            assert (
                m.deadline_exceeded_total.labels("wait")._value.get() == 1
            )
        finally:
            eng.gate.set()
            b.close()

    def test_expired_rider_never_occupies_a_batch_slot(self):
        eng = _GatedEngine()
        eng.gate.set()
        b = CheckBatcher(eng, window_s=0.05)
        try:
            rt = RequestTrace(deadline=Deadline(0.001))
            time.sleep(0.01)  # expire while "queued"
            with pytest.raises(DeadlineExceededError):
                b.check(t("files:x#owner@u"), rt=rt)
            # give the collector a beat: the expired rider must be
            # dropped at the launch boundary, not evaluated
            time.sleep(0.2)
            assert all(
                t("files:x#owner@u") not in batch for batch in eng.batches
            )
        finally:
            b.close()


class TestEngineErrorClassification:
    def test_raw_exception_becomes_typed_keto_error(self):
        class Boom:
            def check_batch(self, tuples, depth):
                raise ValueError("bad graph row")

        m = Metrics()
        b = CheckBatcher(Boom(), window_s=0.0, metrics=m)
        try:
            with pytest.raises(KetoError) as ei:
                b.check(t("files:x#owner@u"))
            assert isinstance(ei.value, CheckBatchFailedError)
            assert ei.value.status == 500
            assert "bad graph row" in ei.value.message
            assert (
                m.check_batch_failed_total.labels("engine")._value.get() == 1
            )
        finally:
            b.close()

    def test_typed_error_passes_through_unwrapped(self):
        from keto_tpu.errors import NamespaceNotFoundError

        class Boom:
            def check_batch(self, tuples, depth):
                raise NamespaceNotFoundError("nope")

        b = CheckBatcher(Boom(), window_s=0.0)
        try:
            with pytest.raises(NamespaceNotFoundError):
                b.check(t("files:x#owner@u"))
        finally:
            b.close()

    def test_still_a_runtime_error_for_embedders(self):
        class Boom:
            def check_batch(self, tuples, depth):
                raise RuntimeError("kernel exploded")

        b = CheckBatcher(Boom(), window_s=0.001)
        try:
            with pytest.raises(RuntimeError, match="kernel exploded"):
                b.check(t("files:x#owner@u"))
        finally:
            b.close()


class _FailingDeviceEngine:
    """Split-phase engine whose device path always raises; the host
    surface answers correctly — the breaker-degradation observable."""

    def __init__(self):
        self.submits = 0
        self.host_batches = 0

    def check_batch_submit(self, tuples, depth=0):
        self.submits += 1
        raise RuntimeError("device wedge")

    def check_batch_host(self, tuples, depth=0):
        self.host_batches += 1
        return [RESULT_IS_MEMBER for _ in tuples]


class TestBreakerInBatcher:
    def test_device_failures_degrade_to_host_then_trip(self):
        eng = _FailingDeviceEngine()
        br = CircuitBreaker(threshold=2, cooldown_s=60.0)
        b = CheckBatcher(eng, window_s=0.0, breaker=br)
        try:
            # failures 1 and 2: device raises, riders are HOST-ANSWERED
            # (graceful degradation), breaker trips on the second
            for _ in range(2):
                res = b.check(t("files:x#owner@u"))
                assert res.membership == Membership.IS_MEMBER
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and br.state != "open":
                time.sleep(0.005)
            assert br.state == "open"
            submits_at_open = eng.submits
            # open: host path only, the device is left alone
            for _ in range(3):
                assert b.check(t("files:x#owner@u")) is RESULT_IS_MEMBER
            assert eng.submits == submits_at_open
            assert eng.host_batches >= 5
        finally:
            b.close()

    def test_half_open_probe_closes_on_success(self):
        class Recovering(_FailingDeviceEngine):
            def __init__(self):
                super().__init__()
                self.healthy = False

            def check_batch_submit(self, tuples, depth=0):
                self.submits += 1
                if not self.healthy:
                    raise RuntimeError("device wedge")
                return list(tuples)

            def check_batch_resolve(self, handle):
                return [RESULT_IS_MEMBER for _ in handle]

        clock = [0.0]
        eng = Recovering()
        br = CircuitBreaker(threshold=1, cooldown_s=1.0, clock=lambda: clock[0])
        b = CheckBatcher(eng, window_s=0.0, breaker=br)
        try:
            b.check(t("files:x#owner@u"))  # trips (host-answered)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and br.state != "open":
                time.sleep(0.005)
            eng.healthy = True
            clock[0] = 1.1  # cooldown over: next group is the probe
            assert b.check(t("files:x#owner@u")) is RESULT_IS_MEMBER
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and br.state != "closed":
                time.sleep(0.005)
            assert br.state == "closed"
            assert list(br.transitions) == ["open", "half_open", "closed"]
        finally:
            b.close()


class TestLaunchWatchdog:
    def test_stalled_launch_recovers_via_host_within_budget(self):
        class Stalling(_FailingDeviceEngine):
            def check_batch_submit(self, tuples, depth=0):
                self.submits += 1
                time.sleep(0.8)
                return list(tuples)

            def check_batch_resolve(self, handle):
                return [RESULT_IS_MEMBER for _ in handle]

        m = Metrics()
        eng = Stalling()
        br = CircuitBreaker(threshold=100)  # observe failures, don't trip
        b = CheckBatcher(
            eng, window_s=0.0, device_timeout_ms=80, breaker=br, metrics=m,
        )
        try:
            t0 = time.perf_counter()
            res = b.check(t("files:x#owner@u"))
            elapsed = time.perf_counter() - t0
            assert res.membership == Membership.IS_MEMBER
            assert elapsed < 0.6  # host-served at ~the watchdog budget
            assert eng.host_batches == 1
            assert (
                m.check_batch_failed_total.labels("device_timeout")
                ._value.get() == 1
            )
            # the abandoned launch's slot was released: a second check
            # still goes through (semaphore not pinned by the wedge)
            assert b.check(t("files:y#owner@u")).allowed is True
            time.sleep(0.9)  # let the stalled submits retire cleanly
        finally:
            b.close()


# ---------------------------------------------------------------------------
# config schema + wiring
# ---------------------------------------------------------------------------


class TestConfigAndWiring:
    def test_schema_accepts_resilience_keys(self):
        Config({"serve": {"check": {
            "max_queue": 128,
            "default_deadline_ms": 500,
            "max_deadline_ms": 2000,
            "device_timeout_ms": 250,
            "breaker": {"threshold": 3, "cooldown_s": 1.5},
        }}})

    def test_schema_rejects_bad_values(self):
        with pytest.raises(ConfigError):
            Config({"serve": {"check": {"max_queue": 0}}})
        with pytest.raises(ConfigError):
            Config({"serve": {"check": {"breaker": {"threshold": 0}}}})
        with pytest.raises(ConfigError):
            Config({"serve": {"check": {"deadline_ms": 5}}})  # typo

    def test_registry_breaker_reads_config(self):
        cfg = Config({"serve": {"check": {
            "breaker": {"threshold": 9, "cooldown_s": 2.5},
        }}})
        reg = Registry(cfg)
        br = reg.circuit_breaker()
        assert br.threshold == 9
        assert br.cooldown_s == 2.5
        assert reg.circuit_breaker() is br  # singleton

    def test_daemon_wires_batcher_resilience(self):
        cfg = Config({
            "dsn": "memory",
            "serve": {
                "check": {"max_queue": 7, "device_timeout_ms": 123},
                "read": {"host": "127.0.0.1", "port": 0},
                "write": {"host": "127.0.0.1", "port": 0},
                "metrics": {"host": "127.0.0.1", "port": 0},
            },
        })
        cfg.set_namespaces(list(NS))
        reg = Registry(cfg)
        d = Daemon(reg)
        try:
            assert d.batcher.max_queue == 7
            assert d.batcher.device_timeout_s == pytest.approx(0.123)
            assert d.batcher.breaker is reg.circuit_breaker()
        finally:
            d.batcher.close()


# ---------------------------------------------------------------------------
# tri-plane typed-error parity (satellite: deadline-exceeded and shed
# responses byte-identical across REST/gRPC/aio, mirroring the cache
# parity tests)
# ---------------------------------------------------------------------------


def _tri_plane_daemon(serve_check: dict):
    cfg = Config({
        "dsn": "memory",
        # parity is about the batcher pipeline's errors: cache off so
        # every check rides it
        "check": {"engine": "tpu", "cache": {"enabled": False}},
        "serve": {
            "read": {
                "host": "127.0.0.1", "port": 0,
                "grpc": {"host": "127.0.0.1", "port": 0, "aio": True},
            },
            "write": {"host": "127.0.0.1", "port": 0},
            "metrics": {"host": "127.0.0.1", "port": 0},
            "check": serve_check,
        },
    })
    cfg.set_namespaces(list(NS))
    reg = Registry(cfg)
    reg.relation_tuple_manager().write_relation_tuples(
        [t("files:doc#owner@alice")]
    )
    # warm the engine (XLA compile) before deadlines/stalls apply
    reg.check_engine().check_batch([t("files:doc#owner@alice")])
    d = Daemon(reg)
    d.start()
    return d


def _rest_check_error(d, subject, headers=None):
    url = (
        f"http://127.0.0.1:{d.read_port}/relation-tuples/check/openapi"
        f"?namespace=files&object=doc&relation=owner&subject_id={subject}"
    )
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read(), {}
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _grpc_check_error(port, subject, timeout=30):
    from keto_tpu.api.descriptors import CHECK_SERVICE, pb

    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        stub = ch.unary_unary(
            f"/{CHECK_SERVICE}/Check",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.CheckResponse.FromString,
        )
        req = pb.CheckRequest()
        req.tuple.namespace = "files"
        req.tuple.object = "doc"
        req.tuple.relation = "owner"
        req.tuple.subject.id = subject
        try:
            stub(req, timeout=timeout)
            return None, None
        except grpc.RpcError as e:
            return e.code(), e.details()
    finally:
        ch.close()


class TestTriPlaneDeadlineParity:
    def test_504_body_and_grpc_code_parity(self):
        # max_deadline_ms clamps the gRPC clients' generous native
        # deadlines down to the server's bound, so the 504s below are
        # deterministically SERVER-side (no client-cancel race)
        d = _tri_plane_daemon(
            {"default_deadline_ms": 150, "max_deadline_ms": 150}
        )
        try:
            faults.set_fault("device_launch", stall_s=0.8)
            t0 = time.perf_counter()
            code, body, _ = _rest_check_error(d, "r1")
            rest_elapsed = time.perf_counter() - t0
            assert code == 504
            parsed = json.loads(body)
            assert parsed["error"]["code"] == 504
            assert parsed["error"]["status"] == "deadline_exceeded"
            assert rest_elapsed < 2 * 0.15 + 0.5
            # no client deadline on the gRPC calls: the 504s below are
            # SERVER-side (the default deadline), so the details string
            # is the server's typed message on both planes
            sync_code, sync_details = _grpc_check_error(d.read_port, "r2")
            aio_code, aio_details = _grpc_check_error(d.read_grpc_port, "r3")
            assert sync_code == grpc.StatusCode.DEADLINE_EXCEEDED
            assert aio_code == grpc.StatusCode.DEADLINE_EXCEEDED
            assert sync_details == aio_details
            assert sync_details == parsed["error"]["message"]
            faults.clear()
            time.sleep(0.9)  # let the stalled launches retire
            # recovery: same daemon answers correctly again
            code, body, _ = _rest_check_error(d, "alice")
            assert code == 200 and json.loads(body) == {"allowed": True}
        finally:
            faults.clear()
            d.stop()


class TestTriPlaneShedParity:
    def test_429_body_and_grpc_code_parity(self):
        d = _tri_plane_daemon({"max_queue": 1})
        try:
            faults.set_fault("device_launch", stall_s=1.2)
            # occupy BOTH planes' single admission slot (the threaded
            # batcher serves REST + muxed gRPC; the aio listener has its
            # own batcher)
            occupiers = [
                threading.Thread(
                    target=lambda: _rest_check_error(
                        d, "alice", headers={"x-request-timeout-ms": "20000"}
                    ),
                    daemon=True,
                ),
                threading.Thread(
                    target=lambda: _grpc_check_error(
                        d.read_grpc_port, "alice", timeout=20
                    ),
                    daemon=True,
                ),
            ]
            for th in occupiers:
                th.start()
            deadline = time.monotonic() + 5
            aio_batcher = d._aio_read.batcher
            while time.monotonic() < deadline and (
                d.batcher._pending < 1 or aio_batcher._pending < 1
            ):
                time.sleep(0.005)
            assert d.batcher._pending >= 1
            assert aio_batcher._pending >= 1
            # REST: two shed responses are byte-identical typed bodies
            code1, body1, hdrs1 = _rest_check_error(d, "s1")
            code2, body2, _ = _rest_check_error(d, "s1")
            assert code1 == code2 == 429
            assert body1 == body2
            parsed = json.loads(body1)
            assert parsed["error"]["status"] == "too_many_requests"
            assert hdrs1.get("Retry-After")
            # gRPC planes agree on code AND details
            sync_code, sync_details = _grpc_check_error(d.read_port, "s2")
            aio_code, aio_details = _grpc_check_error(d.read_grpc_port, "s3")
            assert sync_code == grpc.StatusCode.RESOURCE_EXHAUSTED
            assert aio_code == grpc.StatusCode.RESOURCE_EXHAUSTED
            assert sync_details == aio_details == parsed["error"]["message"]
            # the bound held the whole time
            assert d.batcher._pending <= 1
            assert aio_batcher._pending <= 1
            faults.clear()
            for th in occupiers:
                th.join(timeout=30)
        finally:
            faults.clear()
            d.stop()


# ---------------------------------------------------------------------------
# faults through the real engine (device corruption -> exact host replay)
# ---------------------------------------------------------------------------


class TestEngineFaultPoints:
    def _engine(self):
        from keto_tpu.engine.tpu_engine import TPUCheckEngine
        from keto_tpu.storage.memory import MemoryManager

        cfg = Config({"dsn": "memory"})
        cfg.set_namespaces(list(NS))
        m = MemoryManager()
        m.write_relation_tuples([t("files:doc#owner@alice")])
        return TPUCheckEngine(m, cfg)

    def test_batch_corrupt_forces_exact_host_replay(self):
        eng = self._engine()
        base = eng.check_batch(
            [t("files:doc#owner@alice"), t("files:doc#owner@bob")]
        )
        hosts0 = eng.stats["host_checks"]
        faults.set_fault("batch_corrupt")
        res = eng.check_batch(
            [t("files:doc#owner@alice"), t("files:doc#owner@bob")]
        )
        assert [r.allowed for r in res] == [r.allowed for r in base] == [
            True, False,
        ]
        assert eng.stats["host_checks"] - hosts0 == 2  # all slots replayed

    def test_check_batch_host_is_device_free(self):
        eng = self._engine()
        res = eng.check_batch_host(
            [t("files:doc#owner@alice"), t("files:doc#owner@bob")]
        )
        assert [r.allowed for r in res] == [True, False]
        assert eng.stats["device_checks"] == 0
        assert eng._state is None  # no mirror was ever built

    def test_store_read_fault_reaches_reference_path(self):
        eng = self._engine()
        faults.set_fault("store_read", error="disk gone")
        # a non-direct-hit query must page through get_relation_tuples
        # (a direct hit short-circuits via relation_tuple_exists)
        res = eng.check_batch_host([t("files:doc#owner@bob")])
        assert res[0].error is not None
        assert "disk gone" in str(res[0].error)


class _FakeShedError(_FakeRpcError):
    """UNAVAILABLE shed carrying a Retry-After hint in trailing metadata,
    the way grpc_server._attach_retry_after publishes it."""

    def __init__(self, name, retry_after=None):
        super().__init__(name)
        self._retry_after = retry_after

    def trailing_metadata(self):
        if self._retry_after is None:
            return ()
        return (("retry-after", str(self._retry_after)),)


class TestDecorrelatedRetry:
    """PR 20 client hardening: decorrelated-jitter backoff (no two shed
    clients re-arrive on a synchronized cadence) and the server's
    Retry-After hint flooring the jittered delay."""

    def test_next_delay_stays_in_decorrelated_band(self):
        import random

        pol = RetryPolicy(base_s=0.05, cap_s=2.0, rng=random.Random(3))
        prev = pol.base_s
        for _ in range(200):
            d = pol._next_delay(prev)
            assert pol.base_s <= d <= min(pol.cap_s, prev * 3.0)
            prev = d

    def test_next_delay_capped(self):
        import random

        pol = RetryPolicy(base_s=0.5, cap_s=0.6, rng=random.Random(3))
        # prev * 3 blows far past the cap; the cap must win
        assert all(pol._next_delay(10.0) <= 0.6 for _ in range(50))

    def test_schedules_decorrelate_across_clients(self):
        # Two clients shed at the same instant must NOT re-arrive on the
        # same schedule — that is the whole point of decorrelated jitter
        # over a fixed exponential ladder.
        import random

        def schedule(seed):
            sleeps = []
            pol = RetryPolicy(
                max_attempts=6, base_s=0.01, cap_s=5.0,
                sleep=sleeps.append, rng=random.Random(seed),
            )
            with pytest.raises(_FakeRpcError):
                pol.call(lambda r: (_ for _ in ()).throw(
                    _FakeRpcError("UNAVAILABLE")
                ))
            return sleeps

        a, b = schedule(1), schedule(2)
        assert len(a) == len(b) == 5
        assert a != b

    def test_delay_chain_grows_from_own_prev(self):
        # Each call() keeps its own prev chain: the first delay is drawn
        # from U[base, 3*base], never from another call's history.
        import random

        sleeps = []
        pol = RetryPolicy(
            max_attempts=2, base_s=0.1, cap_s=9.0,
            sleep=sleeps.append, rng=random.Random(5),
        )
        for _ in range(20):
            with pytest.raises(_FakeRpcError):
                pol.call(lambda r: (_ for _ in ()).throw(
                    _FakeRpcError("UNAVAILABLE")
                ))
        assert all(0.1 <= s <= 0.3 for s in sleeps)  # 3 * base, not 3 * prev

    def test_retry_after_metadata_floors_delay(self):
        import random

        sleeps = []
        pol = RetryPolicy(
            max_attempts=3, base_s=0.001, cap_s=2.0,
            sleep=sleeps.append, rng=random.Random(7),
        )
        calls = []

        def fn(remaining):
            calls.append(1)
            if len(calls) < 3:
                raise _FakeShedError("UNAVAILABLE", retry_after=0.5)
            return "ok"

        assert pol.call(fn) == "ok"
        # jitter alone would land near base_s=1ms; the hint floors it
        assert len(sleeps) == 2
        assert all(s >= 0.5 for s in sleeps)

    def test_retry_after_attr_floors_delay(self):
        import random

        class _TypedShed(_FakeRpcError):
            retry_after_s = 0.25

        sleeps = []
        pol = RetryPolicy(
            max_attempts=2, base_s=0.001, cap_s=2.0,
            sleep=sleeps.append, rng=random.Random(7),
        )
        calls = []

        def fn(remaining):
            calls.append(1)
            if len(calls) < 2:
                raise _TypedShed("RESOURCE_EXHAUSTED")
            return "ok"

        assert pol.call(fn) == "ok"
        assert sleeps and sleeps[0] >= 0.25

    def test_hint_counts_against_budget(self):
        # A floored sleep that would outlive the caller's deadline must
        # give up instead of burning the budget asleep.
        sleeps = []
        pol = RetryPolicy(max_attempts=4, base_s=0.001, sleep=sleeps.append)
        with pytest.raises(_FakeShedError):
            pol.call(
                lambda r: (_ for _ in ()).throw(
                    _FakeShedError("UNAVAILABLE", retry_after=10.0)
                ),
                budget_s=0.05,
            )
        assert not sleeps
        assert pol.stats["giveups"] == 1

    def test_hint_parsing(self):
        hint = RetryPolicy.retry_after_hint_s
        assert hint(_FakeShedError("UNAVAILABLE", retry_after=1.5)) == 1.5
        assert hint(_FakeShedError("UNAVAILABLE")) is None
        assert hint(_FakeRpcError("UNAVAILABLE")) is None
        assert hint(_FakeShedError("UNAVAILABLE", retry_after="nonsense")) is None
        assert hint(_FakeShedError("UNAVAILABLE", retry_after=-1)) is None

        class _Typed:
            retry_after_s = 2.0

        assert hint(_Typed()) == 2.0
