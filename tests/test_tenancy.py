"""Per-request tenancy: the Contextualizer hook (ketoctx analog,
/root/reference/ketoctx/contextualizer.go:12-19) serving two isolated
networks through ONE daemon."""

import json
import urllib.error
import urllib.request

import pytest

from keto_tpu.config import Config
from keto_tpu.api.daemon import Daemon
from keto_tpu.ketoapi import RelationTuple
from keto_tpu.ketoctx import DefaultContextualizer, HeaderContextualizer
from keto_tpu.namespace import Namespace
from keto_tpu.registry import Registry


def _cfg():
    cfg = Config({
        "dsn": "memory",
        "check": {"engine": "tpu"},
        "tenancy": {"header": "x-keto-network"},
        "serve": {
            "read": {"host": "127.0.0.1", "port": 0},
            "write": {"host": "127.0.0.1", "port": 0},
            "metrics": {"host": "127.0.0.1", "port": 0},
        },
    })
    cfg.set_namespaces([Namespace(name="files")])
    return cfg


class TestContextualizer:
    def test_header_contextualizer(self):
        c = HeaderContextualizer("X-Keto-Network")
        assert c.network({"x-keto-network": "t1"}, "default") == "t1"
        assert c.network({"X-KETO-NETWORK": "t2"}, "default") == "t2"
        assert c.network({}, "default") == "default"
        assert c.network({"x-keto-network": ""}, "default") == "default"
        assert DefaultContextualizer().network({"x-keto-network": "t"}, "d") == "d"

    def test_registry_builds_contextualizer_from_config(self):
        reg = Registry(_cfg())
        assert reg.nid_for({"x-keto-network": "tenant-a"}) == "tenant-a"
        assert reg.nid_for({}) == reg.nid
        assert reg.nid_for(None) == reg.nid

    def test_per_nid_engine_cache(self):
        reg = Registry(_cfg())
        e_default = reg.check_engine()
        e_a = reg.check_engine("tenant-a")
        e_b = reg.check_engine("tenant-b")
        assert e_a is not e_b and e_a is not e_default
        assert reg.check_engine("tenant-a") is e_a
        assert reg.check_engine(reg.nid) is e_default


class TestTwoTenantDaemon:
    def test_isolation_through_one_daemon(self):
        reg = Registry(_cfg())
        d = Daemon(reg)
        d.start()
        try:
            write = f"http://127.0.0.1:{d.write_port}/admin/relation-tuples"
            read = (
                f"http://127.0.0.1:{d.read_port}/relation-tuples/check/openapi"
                "?namespace=files&object=doc&relation=owner&subject_id=alice"
            )

            def put(tenant):
                req = urllib.request.Request(
                    write,
                    data=json.dumps(
                        RelationTuple.from_string("files:doc#owner@alice").to_dict()
                    ).encode(),
                    method="PUT",
                    headers={"x-keto-network": tenant},
                )
                return urllib.request.urlopen(req).status

            def check(tenant):
                req = urllib.request.Request(
                    read, headers={"x-keto-network": tenant}
                )
                return json.load(urllib.request.urlopen(req))["allowed"]

            assert put("tenant-a") == 201
            assert check("tenant-a") is True
            # the other tenant and the default network see nothing
            assert check("tenant-b") is False
            req = urllib.request.Request(read)
            assert json.load(urllib.request.urlopen(req))["allowed"] is False
            # read API is scoped too
            lst = urllib.request.Request(
                f"http://127.0.0.1:{d.read_port}/relation-tuples?namespace=files",
                headers={"x-keto-network": "tenant-b"},
            )
            assert json.load(urllib.request.urlopen(lst))["relation_tuples"] == []
        finally:
            d.stop()


class TestTenancyHardening:
    def test_malformed_nid_rejected(self):
        from keto_tpu.errors import MalformedInputError

        reg = Registry(_cfg())
        for bad in ("../../etc", "a/b", "x" * 200, "a b", ""):
            if bad == "":
                # empty header falls back to the default network
                assert reg.nid_for({"x-keto-network": ""}) == reg.nid
                continue
            with pytest.raises(MalformedInputError):
                reg.nid_for({"x-keto-network": bad})

    def test_engine_cache_lru_bound(self):
        cfg = _cfg()
        cfg.set("tenancy.max_networks", 3)
        reg = Registry(cfg)
        engines = {t: reg.check_engine(t) for t in ("a", "b", "c")}
        assert len(reg._nid_engines) == 3
        reg.check_engine("d")  # evicts "a" (LRU)
        assert "a" not in reg._nid_engines
        assert len(reg._nid_engines) == 3
        # "b" is still cached (same object), and re-use refreshes it
        assert reg.check_engine("b") is engines["b"]
        reg.check_engine("e")  # now "c" is the oldest
        assert "c" not in reg._nid_engines and "b" in reg._nid_engines

    def test_malformed_nid_is_400_through_daemon(self):
        reg = Registry(_cfg())
        d = Daemon(reg)
        d.start()
        try:
            read = (
                f"http://127.0.0.1:{d.read_port}/relation-tuples/check/openapi"
                "?namespace=files&object=doc&relation=owner&subject_id=alice"
            )
            req = urllib.request.Request(
                read, headers={"x-keto-network": "../../../tmp/evil"}
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req)
            assert e.value.code == 400
        finally:
            d.stop()
