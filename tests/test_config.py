"""Config provider + namespace manager tests (hot reload, OPL wiring,
immutable keys). Mirrors internal/driver/config behaviors."""

import os
import time

import pytest

from keto_tpu.config import Config, ConfigError, NamespaceFileManager
from keto_tpu.errors import NamespaceNotFoundError
from keto_tpu.namespace import Namespace
from keto_tpu.namespace.ast import ComputedSubjectSet, Relation


class TestConfig:
    def test_defaults(self):
        c = Config()
        assert c.max_read_depth() == 5
        assert c.read_api_address().port == 4466
        assert c.write_api_address().port == 4467
        assert c.metrics_api_address().port == 4468
        assert c.page_size() == 100
        assert c.dsn == "memory"

    def test_inline_namespaces(self):
        c = Config(
            {
                "namespaces": [
                    {"name": "videos", "id": 0},
                    {
                        "name": "files",
                        "relations": [
                            {"name": "owner"},
                            {
                                "name": "view",
                                "rewrite": {
                                    "operator": "or",
                                    "children": [{"relation": "owner"}],
                                },
                            },
                        ],
                    },
                ]
            }
        )
        nm = c.namespace_manager()
        assert nm.get_namespace_by_name("videos").name == "videos"
        assert nm.get_namespace_by_config_id(0).name == "videos"
        files = nm.get_namespace_by_name("files")
        rw = files.relation("view").subject_set_rewrite
        assert isinstance(rw.children[0], ComputedSubjectSet)
        with pytest.raises(NamespaceNotFoundError):
            nm.get_namespace_by_name("nope")

    def test_immutable_keys(self):
        c = Config({"dsn": "memory"})
        with pytest.raises(ConfigError):
            c.set("dsn", "other")
        c.set("limit.max_read_depth", 10)
        assert c.max_read_depth() == 10

    def test_set_namespaces_programmatically(self):
        c = Config()
        c.set_namespaces([Namespace(name="n", relations=[Relation(name="r")])])
        assert c.namespace_manager().get_namespace_by_name("n").relation("r")


class TestNamespaceFiles:
    def test_yaml_file(self, tmp_path):
        p = tmp_path / "ns.yml"
        p.write_text("name: videos\nid: 3\n")
        m = NamespaceFileManager(str(p))
        assert m.get_namespace_by_name("videos").id == 3

    def test_directory_and_opl(self, tmp_path):
        (tmp_path / "a.json").write_text('{"name": "a"}')
        (tmp_path / "b.ts").write_text(
            """
            class User implements Namespace {}
            class Doc implements Namespace {
              related: { owners: User[] }
              permits = { view: (ctx) => this.related.owners.includes(ctx.subject) }
            }
            """
        )
        m = NamespaceFileManager(str(tmp_path))
        names = sorted(n.name for n in m.namespaces())
        assert names == ["Doc", "User", "a"]
        doc = m.get_namespace_by_name("Doc")
        assert doc.relation("view").subject_set_rewrite is not None

    def test_hot_reload_and_rollback(self, tmp_path):
        p = tmp_path / "ns.json"
        p.write_text('{"name": "one"}')
        m = NamespaceFileManager(str(p))
        assert m.get_namespace_by_name("one")

        # hot reload on mtime change
        p.write_text('{"name": "two"}')
        os.utime(p, (time.time() + 5, time.time() + 5))
        assert m.get_namespace_by_name("two")
        with pytest.raises(NamespaceNotFoundError):
            m.get_namespace_by_name("one")

        # parse error → rollback to previous set (namespace_watcher.go:118-137)
        p.write_text("{not json")
        os.utime(p, (time.time() + 10, time.time() + 10))
        assert m.get_namespace_by_name("two")

    def test_config_file_namespace_location(self, tmp_path):
        ns = tmp_path / "ns.yml"
        ns.write_text("name: videos\n")
        cfg = tmp_path / "keto.yml"
        cfg.write_text(
            f"namespaces: file://{ns}\nlimit:\n  max_read_depth: 7\ndsn: memory\n"
        )
        c = Config.from_file(str(cfg))
        assert c.max_read_depth() == 7
        assert c.namespace_manager().get_namespace_by_name("videos")
