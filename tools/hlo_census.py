"""Optimized-HLO op census for the check kernel's while-loop body.

The round-4 profile showed the BFS step is op-overhead bound (~3.5 ms
fixed per step at F=4k, +40% at 8x F). Before building any Pallas
replacement, this tool answers: WHICH ops make up the step? It AOT
lowers+compiles check_kernel for the current backend, extracts the
while-loop body computation from the optimized HLO, and prints a census
of op counts grouped by opcode (fusions counted as one boundary each,
with their root op noted).

    python tools/hlo_census.py [--frontier 16384] [--batch 4096] [--out f]

Works against the axon TPU tunnel (compile is server-side; as_text
returns the optimized module) or JAX_PLATFORMS=cpu for a rough look.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--frontier", type=int, default=16384)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--out", default=None, help="also dump full HLO text here")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from keto_tpu.engine.kernel import (
        check_kernel,
        kernel_static_config,
        snapshot_tables,
    )
    from keto_tpu.engine.snapshot import build_snapshot

    namespaces, tuples, _ = bench.build_dataset()
    snap = build_snapshot(tuples, namespaces)
    tables = snapshot_tables(snap)
    statics = kernel_static_config(snap, 5, args.frontier)

    B = args.batch
    qz = jnp.zeros(B, jnp.int32)
    lowered = check_kernel.lower(
        tables, qz, qz, qz + 5, qz, qz, qz, jnp.ones(B, bool), **statics
    )
    compiled = lowered.compile()
    txt = compiled.as_text()
    if args.out:
        with open(args.out, "w") as f:
            f.write(txt)

    # find the while body computation: the body referenced by the while op
    m = re.search(r"while\(.*\), condition=.*, body=([%\w.-]+)", txt)
    body_name = m.group(1).lstrip("%") if m else None
    # split computations
    comps = {}
    cur = None
    for line in txt.splitlines():
        cm = re.match(r"^[%]?([\w.-]+) \([\w.]*: ", line) or re.match(
            r"^(?:ENTRY )?[%]?([\w.-]+) \(", line
        )
        if cm and ("{" in line or line.rstrip().endswith("{")):
            cur = cm.group(1)
            comps[cur] = []
        elif cur is not None:
            comps[cur].append(line)

    def census(name):
        ops = collections.Counter()
        fusion_roots = collections.Counter()
        lines = comps.get(name, [])
        for line in lines:
            om = re.match(r"\s+(?:ROOT )?[%]?[\w.-]+ = [^ ]+ ([\w-]+)\(", line)
            if not om:
                continue
            op = om.group(1)
            if op in ("parameter", "constant", "tuple", "get-tuple-element",
                      "bitcast"):
                continue
            ops[op] += 1
            if op == "fusion":
                rm = re.search(r"calls=([%\w.-]+)", line)
                if rm:
                    # root op of the called fusion computation
                    fl = comps.get(rm.group(1).lstrip("%"), [])
                    for l in fl:
                        if "ROOT" in l:
                            r = re.match(
                                r"\s+ROOT [%]?[\w.-]+ = [^ ]+ ([\w-]+)\(", l
                            )
                            if r:
                                fusion_roots[r.group(1)] += 1
        return ops, fusion_roots

    if body_name is None:
        # fall back: largest computation
        body_name = max(comps, key=lambda k: len(comps[k]))
    ops, roots = census(body_name)
    total = sum(ops.values())
    print(json.dumps({
        "body": body_name,
        "total_boundaries": total,
        "ops": dict(ops.most_common()),
        "fusion_roots": dict(roots.most_common()),
        "device": str(jax.devices()[0]),
        "frontier": args.frontier,
        "batch": B,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
