"""Open-loop load generator for the serving plane.

The r04 served numbers were CLOSED-loop: N clients each waiting for
their previous response, so offered load is capped at N / latency and a
slow server hides its own queueing (coordinated omission). This drives
the read plane OPEN-loop: requests are scheduled on a fixed timeline at
`--rate` regardless of completions, so latency-under-load and the
saturation knee are visible.

Two request shapes:
  --mode single   one check per RPC (the v1alpha2 parity surface)
  --mode batch    one BatchCheck RPC per tick carrying --batch checks
                  (the keto_tpu extension; offered checks/s =
                  rate * batch)

    python tools/load_gen.py --addr 127.0.0.1:4466 --rate 200 \
        --seconds 10 --mode batch --batch 512

Prints one JSON line: offered vs achieved rate, completion latency
percentiles (measured from SCHEDULED send time — queueing delay from a
saturated server counts, as it should), error/timeout counts.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--addr", default="127.0.0.1:4466")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="request ticks per second (open-loop schedule)")
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--mode", choices=("single", "batch"), default="single")
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--workers", type=int, default=64,
                    help="in-flight cap (past it, ticks count as shed)")
    ap.add_argument("--queries", default=None,
                    help="JSON file of relation tuples; default: the "
                         "bench dataset's query mix")
    ap.add_argument("--record", default=None, metavar="OUT_JSON",
                    help="also write the result record to this file — "
                         "the committed-artifact mode (saturation curves "
                         "land in the repo, not just a terminal scroll)")
    args = ap.parse_args()

    from keto_tpu.api import ReadClient, open_channel
    from keto_tpu.ketoapi import RelationTuple

    if args.queries:
        with open(args.queries) as f:
            queries = [RelationTuple.from_dict(d) for d in json.load(f)]
    else:
        import bench

        _, _, queries = bench.build_dataset()

    rng = random.Random(0)
    qn = len(queries)

    # a small client pool: gRPC channels multiplex, but one channel's
    # Python-side completion queue serializes; a handful spreads it
    clients = [ReadClient(open_channel(args.addr)) for _ in range(8)]

    lock = threading.Lock()
    lat: list[float] = []
    errors = [0]
    checks_done = [0]
    shed = [0]
    inflight = threading.Semaphore(args.workers)

    def fire(scheduled: float, client: ReadClient) -> None:
        try:
            if args.mode == "single":
                q = queries[rng.randrange(qn)]
                client.check(q, timeout=args.timeout)
                n = 1
            else:
                start = rng.randrange(qn)
                qs = [queries[(start + j) % qn] for j in range(args.batch)]
                client.check_batch(qs, timeout=args.timeout)
                n = args.batch
            done = time.perf_counter()
            with lock:
                lat.append(done - scheduled)
                checks_done[0] += n
        except Exception:
            with lock:
                errors[0] += 1
        finally:
            inflight.release()

    n_ticks = int(args.rate * args.seconds)
    interval = 1.0 / args.rate
    t0 = time.perf_counter()
    threads: list[threading.Thread] = []
    for i in range(n_ticks):
        scheduled = t0 + i * interval
        now = time.perf_counter()
        if scheduled > now:
            time.sleep(scheduled - now)
        if not inflight.acquire(blocking=False):
            with lock:
                shed[0] += 1
            continue
        th = threading.Thread(
            target=fire, args=(scheduled, clients[i % len(clients)]),
            daemon=True,
        )
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=args.timeout + 5)
    wall = time.perf_counter() - t0
    for c in clients:
        c.close()

    import numpy as np

    out = {
        "mode": args.mode,
        "offered_rps": args.rate,
        "offered_checks_per_s": args.rate * (
            1 if args.mode == "single" else args.batch
        ),
        "achieved_checks_per_s": round(checks_done[0] / wall, 1),
        "completed_rpcs": len(lat),
        "errors": errors[0],
        "shed_ticks": shed[0],
        "wall_s": round(wall, 2),
    }
    if lat:
        a = np.array(lat) * 1e3
        out.update({
            "lat_p50_ms": round(float(np.percentile(a, 50)), 2),
            "lat_p95_ms": round(float(np.percentile(a, 95)), 2),
            "lat_p99_ms": round(float(np.percentile(a, 99)), 2),
        })
    print(json.dumps(out))
    if args.record:
        with open(args.record, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
