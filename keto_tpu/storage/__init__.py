from .definitions import Manager, DEFAULT_PAGE_SIZE
from .memory import MemoryManager
from .sqlite import SQLitePersister
from .mapping import UUIDMappingManager, Mapper

__all__ = [
    "Manager",
    "MemoryManager",
    "SQLitePersister",
    "UUIDMappingManager",
    "Mapper",
    "DEFAULT_PAGE_SIZE",
]
