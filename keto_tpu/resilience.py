"""Overload & failure resilience plane: deadlines, admission, breaker, retry.

Zanzibar keeps its tail latency bounded under overload by bounding the
work any one request can consume — deadline-scoped evaluation, request
hedging, and graceful degradation (paper §2.4.1/§4) — and the Go
reference gets request cancellation for free via `context.Context`. This
module is the Python equivalent for the serve plane, four primitives the
transports and batchers share:

  - `Deadline` — one end-to-end budget per request, ingested at the
    transport (REST `x-request-timeout-ms`, native gRPC deadlines,
    `serve.check.default_deadline_ms`, clamped to
    `serve.check.max_deadline_ms`), carried on the RequestTrace handoff,
    and checked at every stage boundary (admission -> queue -> device
    wait) so an expired request fails fast with a typed
    `DeadlineExceededError` instead of occupying a batch slot.
  - `admit_check` — the admission gate all three transports run BEFORE
    any work: rejects with a typed `OverloadedError` while the daemon
    drains or when the batcher's admitted-but-unresolved count is at
    `serve.check.max_queue` (queue-delay-aware: the retry-after hint is
    the estimated queue delay).
  - `CircuitBreaker` — the device-path breaker: consecutive device-batch
    failures or launch timeouts trip closed -> open; while open every
    check routes to the exact host oracle (answers stay correct, latency
    degrades); after `cooldown_s` one probe batch half-opens it and its
    outcome closes or re-opens.
  - `RetryPolicy` — client-side exponential backoff with FULL jitter for
    idempotent reads only (`ReadClient`), retrying UNAVAILABLE /
    RESOURCE_EXHAUSTED within the caller's deadline budget.

Everything is dependency-light (no grpc/jax imports at module level) so
the CLI and tools can use the backoff helpers standalone.
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Iterator, Optional

from .errors import (
    DeadlineExceededError,
    FilterTooLargeError,
    MalformedInputError,
    OverloadedError,
)

# -- deadlines ----------------------------------------------------------------


class Deadline:
    """One request's end-to-end budget, pinned to the monotonic clock at
    ingestion. Cheap by design: the hot path asks only remaining_s() /
    expired() (two clock reads per stage boundary)."""

    __slots__ = ("expires_at", "budget_s")

    def __init__(self, budget_s: float):
        self.budget_s = float(budget_s)
        self.expires_at = time.monotonic() + self.budget_s

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(float(ms) / 1e3)

    def remaining_s(self) -> float:
        return max(0.0, self.expires_at - time.monotonic())

    def expired(self) -> bool:
        return time.monotonic() >= self.expires_at


def parse_timeout_ms(value: Optional[str]) -> Optional[float]:
    """The REST `x-request-timeout-ms` header value as milliseconds; a
    malformed or non-positive value is the client's error (400), never a
    silent no-deadline."""
    if not value:
        return None
    try:
        ms = float(value)
    except ValueError:
        raise MalformedInputError(
            debug=f"invalid x-request-timeout-ms {value!r}"
        )
    if ms <= 0:
        raise MalformedInputError(
            debug=f"x-request-timeout-ms must be positive, got {value!r}"
        )
    return ms


def ingest_deadline(
    config, request_ms: Optional[float] = None,
    native_s: Optional[float] = None,
) -> Optional[Deadline]:
    """Build one request's Deadline from (in precedence order) the
    explicit request budget (REST header ms / native gRPC seconds) and
    the `serve.check.default_deadline_ms` schema key, clamped to
    `serve.check.max_deadline_ms`. None = no deadline (parity with the
    reference, whose REST plane has none either)."""
    budget_ms = request_ms
    if budget_ms is None and native_s is not None:
        if native_s <= 0:
            # the client's deadline already expired in transit: an
            # ALREADY-EXPIRED deadline (admit_check 504s before any
            # work), not "no deadline"
            return Deadline(0.0)
        # some grpc versions answer time_remaining() with a sentinel-huge
        # float instead of None when the client set no deadline —
        # anything past a day is "no deadline", not a budget (and would
        # overflow the C-level wait timeouts downstream)
        if native_s < 86400.0:
            budget_ms = native_s * 1e3
    if budget_ms is None:
        default_ms = config.get("serve.check.default_deadline_ms")
        if default_ms:
            budget_ms = float(default_ms)
    if budget_ms is None:
        return None
    max_ms = config.get("serve.check.max_deadline_ms")
    if max_ms:
        budget_ms = min(budget_ms, float(max_ms))
    # absolute cap (one day): an absurd client budget must not overflow
    # the C-level wait timeouts the remaining_s value feeds
    return Deadline.after_ms(min(budget_ms, 86400.0 * 1e3))


def admit_check(registry, batcher, rt=None) -> None:
    """The shared admission gate, run by all three transports BEFORE any
    check work (cache lookup included): typed rejection while the daemon
    drains, when the request arrived already expired, or when the
    batcher is at its admission bound. Raises OverloadedError (429 /
    RESOURCE_EXHAUSTED) or DeadlineExceededError (504 /
    DEADLINE_EXCEEDED); byte-identical bodies across REST/gRPC/aio
    because all planes map the same KetoError."""
    metrics = registry.metrics()
    if registry.draining.is_set():
        metrics.requests_shed_total.labels("draining").inc()
        raise OverloadedError(
            "server is draining", retry_after_s=1.0
        )
    dl = getattr(rt, "deadline", None) if rt is not None else None
    if dl is not None and dl.expired():
        metrics.deadline_exceeded_total.labels("admission").inc()
        raise DeadlineExceededError(
            "request deadline expired before admission"
        )
    if batcher is not None:
        batcher.admit(dl)


DEFAULT_FILTER_MAX_OBJECTS = 65536


def admit_filter(registry, n_objects: int, rt=None) -> None:
    """The BatchFilter admission gate, run by all three transports
    BEFORE any filter work: the shared draining/expired checks
    (admit_check semantics — typed 429/504), plus the candidate-list
    bound from `filter.max_objects` — an oversized request sheds a typed
    400 (FilterTooLargeError) rather than buying unbounded device work.
    Byte-identical bodies across REST/gRPC/aio because all planes map
    the same KetoError."""
    admit_check(registry, None, rt)
    max_objects = int(
        registry.config.get("filter.max_objects", DEFAULT_FILTER_MAX_OBJECTS)
    )
    if n_objects > max_objects:
        registry.metrics().filter_shed_total.labels("max_objects").inc()
        raise FilterTooLargeError(
            f"filter candidate list has {n_objects} objects; "
            f"filter.max_objects allows {max_objects} — split the list "
            "and chain the response snaptoken"
        )


DEFAULT_EXPLAIN_MAX_PER_S = 10.0


class TokenBucket:
    """Plain token-bucket rate limiter (monotonic clock, thread-safe):
    `rate` tokens refill per second up to `burst`. `try_take()` is the
    whole hot surface — (admitted, retry_after_s). Built for the explain
    plane's admission bound, generic by construction."""

    def __init__(self, rate_per_s: float, burst: Optional[float] = None,
                 clock=time.monotonic):
        self.rate = max(float(rate_per_s), 1e-6)
        self.burst = float(burst) if burst is not None else max(
            self.rate, 1.0
        )
        self._clock = clock
        self._mu = threading.Lock()
        self._tokens = self.burst
        self._last = clock()

    def try_take(self, n: float = 1.0):
        """(True, 0.0) and one token consumed, or (False, seconds until
        a token will exist) with nothing consumed."""
        with self._mu:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            return False, (n - self._tokens) / self.rate


def admit_explain(registry, rt=None) -> None:
    """The explain plane's admission gate: the shared draining/expired
    checks (admit_check semantics, typed 429/504), plus the
    `explain.max_per_s` token bucket — explain bypasses the check cache
    and pays a host witness re-walk per request, so the slow path is
    rate-bounded before any work (a typed 429 with the bucket's refill
    time as Retry-After; counted under
    keto_tpu_requests_shed_total{explain_rate}). Byte-identical bodies
    across REST/gRPC/aio because all planes map the same KetoError."""
    admit_check(registry, None, rt)
    admitted, retry_after = registry.explain_limiter().try_take()
    if not admitted:
        registry.metrics().requests_shed_total.labels("explain_rate").inc()
        raise OverloadedError(
            "explain rate limit exceeded (explain.max_per_s) — retry "
            "later or lower the explain volume",
            retry_after_s=retry_after,
        )


def retry_after_header_value(retry_after_s: Optional[float]) -> str:
    """Retry-After is specified in whole seconds; round up so the hint
    never invites an immediately-reshed retry."""
    if not retry_after_s or retry_after_s <= 0:
        return "1"
    return str(max(1, int(math.ceil(retry_after_s))))


# -- backoff / client retry ---------------------------------------------------


def backoff_delays(
    base_s: float = 0.25,
    cap_s: float = 5.0,
    rng: Optional[random.Random] = None,
) -> Iterator[float]:
    """Infinite exponential backoff with FULL jitter (delay ~ U[0, min(
    cap, base * 2^attempt)]) — the AWS-architecture-blog shape: under a
    thundering herd, full jitter spreads retries across the whole window
    instead of synchronizing them at the cap."""
    rng = rng or random.Random()
    attempt = 0
    while True:
        yield rng.uniform(0.0, min(cap_s, base_s * (2.0 ** attempt)))
        if base_s * (2.0 ** attempt) < cap_s:
            attempt += 1


class RetryPolicy:
    """Client-side retry for IDEMPOTENT reads only (ReadClient wires it;
    WriteClient never does — a retried transact could double-apply).

    Retries gRPC UNAVAILABLE / RESOURCE_EXHAUSTED (the two codes this
    server sheds with) with DECORRELATED-jitter backoff (delay ~
    U[base, 3 * previous], capped) — unlike a fixed exponential ladder,
    no two clients that failed at the same instant re-arrive on the
    same schedule, so a shedding daemon is never hammered at a
    synchronized cadence. When the server attached a `Retry-After` hint
    (the typed 503/429 sheds carry one in gRPC trailing metadata and
    the REST header), that hint FLOORS the backoff: the server said how
    long the condition lasts, and retrying earlier is a wasted shed.
    Both stay inside the caller's deadline budget: a retry whose sleep
    would outlive the remaining budget gives up and re-raises instead
    of burning the budget asleep. `counter` is an optional metrics
    counter (e.g. Metrics.client_retries_total) incremented per retry;
    `stats` mirrors it process-locally."""

    RETRYABLE_CODES = ("UNAVAILABLE", "RESOURCE_EXHAUSTED")

    def __init__(
        self,
        max_attempts: int = 3,
        base_s: float = 0.05,
        cap_s: float = 2.0,
        codes=None,
        counter=None,
        sleep=time.sleep,
        rng: Optional[random.Random] = None,
    ):
        self.max_attempts = max(int(max_attempts), 1)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.codes = tuple(codes) if codes is not None else self.RETRYABLE_CODES
        self.counter = counter
        self._sleep = sleep
        self._rng = rng or random.Random()
        self.stats = {"attempts": 0, "retries": 0, "giveups": 0}

    def _next_delay(self, prev: float) -> float:
        """Decorrelated jitter (the AWS-architecture-blog variant):
        delay ~ U[base, 3 * previous], capped. Each call() keeps its OWN
        `prev` chain, so concurrent requests through one shared policy
        never couple their schedules."""
        return min(self.cap_s, self._rng.uniform(self.base_s, prev * 3.0))

    def _retryable(self, err) -> bool:
        code = getattr(err, "code", None)
        if not callable(code):
            return False
        try:
            name = code().name
        except Exception:  # noqa: BLE001 — malformed RpcError: don't retry
            return False
        return name in self.codes

    @staticmethod
    def retry_after_hint_s(err) -> Optional[float]:
        """The server's Retry-After hint riding a shed, in seconds:
        gRPC errors carry it as `retry-after` trailing metadata
        (grpc_server._attach_retry_after); typed KetoErrors carry
        `retry_after_s` directly (REST clients mapping the header).
        None when the error carries no hint."""
        direct = getattr(err, "retry_after_s", None)
        if isinstance(direct, (int, float)) and direct > 0:
            return float(direct)
        trailing = getattr(err, "trailing_metadata", None)
        if not callable(trailing):
            return None
        try:
            for key, value in trailing() or ():
                if key == "retry-after":
                    parsed = float(value)
                    return parsed if parsed > 0 else None
        except Exception:  # noqa: BLE001 — malformed metadata: no hint
            return None
        return None

    def call(self, fn, budget_s: Optional[float] = None):
        """Run `fn(remaining_timeout_s)` with retries. The budget is the
        TOTAL deadline across all attempts (the caller's `timeout=`);
        each attempt gets the remaining slice, so retries never extend
        the caller-visible deadline."""
        start = time.monotonic()
        attempt = 0
        prev_delay = self.base_s
        while True:
            self.stats["attempts"] += 1
            remaining = (
                None if budget_s is None
                else budget_s - (time.monotonic() - start)
            )
            try:
                return fn(remaining)
            except Exception as e:  # noqa: BLE001 — classified just below
                if not self._retryable(e) or attempt + 1 >= self.max_attempts:
                    raise
                prev_delay = delay = self._next_delay(prev_delay)
                hint = self.retry_after_hint_s(e)
                if hint is not None:
                    # the hint is a FLOOR, not a replacement: jitter
                    # still spreads clients that were shed together
                    delay = max(delay, hint)
                if remaining is not None and delay >= max(remaining, 0.0):
                    # budget-aware: sleeping would outlive the deadline
                    self.stats["giveups"] += 1
                    raise
                self.stats["retries"] += 1
                if self.counter is not None:
                    self.counter.inc()
                self._sleep(delay)
                attempt += 1


# -- circuit breaker ----------------------------------------------------------


class CircuitBreaker:
    """Device-path circuit breaker: closed -> open -> half-open.

    `record_failure()` on consecutive device-batch failures (submit /
    resolve exceptions, launch watchdog timeouts); at `threshold` the
    breaker OPENS and `allow()` answers False — the batchers then route
    every check group to the exact host oracle (engine/reference.py):
    answers stay correct, latency degrades, the device is left alone to
    recover. After `cooldown_s` the next `allow()` admits exactly ONE
    probe group (half-open); its `record_success()` closes the breaker,
    its `record_failure()` re-opens it for another cooldown.

    Thread-safe (one lock, a handful of fields) and shared by both
    batching planes so the device's health is judged from all traffic.
    State is exported as `keto_tpu_breaker_state` (0 closed / 1 open /
    2 half-open) plus a transitions counter, so the closed -> open ->
    half-open -> closed recovery is observable from /metrics/prometheus.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
    _STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

    def __init__(
        self,
        threshold: int = 5,
        cooldown_s: float = 5.0,
        metrics=None,
        clock=time.monotonic,
    ):
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = float(cooldown_s)
        self.metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._open_until = 0.0
        # trip() floor: while the clock is below it, record_success from
        # batches launched BEFORE the trip must not close the breaker
        self._floor_until = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0
        # bounded transition trail (tests/smoke observability; a
        # persistently flapping breaker must not grow a list forever —
        # long-horizon counting is breaker_transitions_total's job)
        import collections

        self.transitions: "collections.deque[str]" = collections.deque(
            maxlen=64
        )
        if metrics is not None:
            metrics.breaker_state.set(0)

    # -- internals (caller holds self._lock) ----------------------------------

    def _transition(self, to: str) -> None:
        self._state = to
        self.transitions.append(to)
        if self.metrics is not None:
            self.metrics.breaker_state.set(self._STATE_CODE[to])
            self.metrics.breaker_transitions_total.labels(to).inc()

    # -- batcher surface ------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May this check group take the device path? Consumes the
        half-open probe slot when it grants one — call once per group.
        A probe that never reports an outcome (its riders all expired at
        the launch boundary, or the engine failed before any device
        contact) is RECLAIMED after one cooldown, so a lost probe can
        stall recovery by at most cooldown_s — never forever."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = self._clock()
            if self._state == self.OPEN:
                if now < self._open_until:
                    return False
                self._transition(self.HALF_OPEN)
                self._probe_inflight = True
                self._probe_started = now
                return True
            # half-open: exactly one probe at a time (stale probes
            # reclaimed after a cooldown, see docstring)
            if (
                self._probe_inflight
                and now - self._probe_started < self.cooldown_s
            ):
                return False
            self._probe_inflight = True
            self._probe_started = now
            return True

    def open_remaining_s(self) -> float:
        """Seconds until an open breaker admits its half-open probe
        (0.0 when not open) — the Retry-After hint a breaker-open
        rejection carries, so clients back off until recovery is even
        possible instead of hammering the fail-fast path."""
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(0.0, self._open_until - self._clock())

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state == self.CLOSED:
                return
            # a trip() floor holds the breaker open against successes
            # from batches that were already in flight when the trip
            # landed: their outcome says nothing about the condition
            # (e.g. mirror divergence) the tripper detected. Recovery
            # then rides the normal cooldown -> half-open probe, which
            # allow() only grants after the floor has passed.
            if self._clock() < self._floor_until:
                return
            self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_inflight = False
            if self._state == self.HALF_OPEN:
                self._open_until = self._clock() + self.cooldown_s
                self._transition(self.OPEN)
                return
            self._failures += 1
            if self._state == self.CLOSED and self._failures >= self.threshold:
                self._open_until = self._clock() + self.cooldown_s
                self._transition(self.OPEN)

    def trip(self, cooldown_s: Optional[float] = None) -> None:
        """Open the breaker NOW, unconditionally — the degrade entry
        point for detectors that established device-path unhealthiness
        out of band (the anti-entropy scrubber on mirror divergence:
        consecutive-failure counting is meaningless when the evidence is
        a checksum, not a request). Checks host-oracle-serve for the
        cooldown; the usual half-open probe then decides recovery
        against the rebuilt mirror."""
        with self._lock:
            self._probe_inflight = False
            self._open_until = self._clock() + (
                self.cooldown_s if cooldown_s is None else float(cooldown_s)
            )
            self._floor_until = self._open_until
            if self._state != self.OPEN:
                self._transition(self.OPEN)
