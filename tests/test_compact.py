"""Incremental compaction (engine/compact.py): delta-overlay overflow
merges pending ops into the base mirror instead of a full rebuild.

The write-churn cliff this covers: at 1e7+ tuples a full rebuild is
minutes of host work, so an oversized delta used to mean a multi-minute
staleness window (round-3 VERDICT weak item 3). Every test here asserts
BOTH the mechanism (stats counters: merged, not rebuilt) and the
semantics (differential vs the exact host ReferenceEngine — the same
oracle discipline as tests/test_kernel.py).
"""

import random

import numpy as np

from keto_tpu.config import Config
from keto_tpu.engine import Membership
from keto_tpu.engine.compact import merge_ops_into_snapshot
from keto_tpu.engine.delta import DELTA_COMPACT_THRESHOLD
from keto_tpu.engine.reference import ReferenceEngine
from keto_tpu.engine.snapshot import ArrayMap, build_snapshot
from keto_tpu.engine.tpu_engine import TPUCheckEngine
from keto_tpu.ketoapi import RelationTuple
from keto_tpu.namespace import Namespace
from keto_tpu.namespace.ast import (
    ComputedSubjectSet,
    Relation,
    SubjectSetRewrite,
    TupleToSubjectSet,
)
from keto_tpu.storage import MemoryManager
from keto_tpu.storage.columnar import ColumnarStore

NS = [Namespace(name="f", relations=[
    Relation(name="owner"),
    Relation(name="parent"),
    Relation(name="member"),
    Relation(name="view", subject_set_rewrite=SubjectSetRewrite(children=[
        ComputedSubjectSet(relation="owner"),
        TupleToSubjectSet(relation="parent",
                          computed_subject_set_relation="view"),
    ])),
])]

OVERFLOW = DELTA_COMPACT_THRESHOLD + 8  # one past the overlay capacity


def ts(*strs):
    return [RelationTuple.from_string(s) for s in strs]


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


def make_engine(store=None, tuples=(), max_depth=6):
    cfg = Config({"limit": {"max_read_depth": max_depth}})
    cfg.set_namespaces(NS)
    m = store if store is not None else MemoryManager()
    if tuples:
        m.write_relation_tuples(list(tuples))
    return TPUCheckEngine(m, cfg)


def overflow_writes(prefix="bulk", n=OVERFLOW):
    return [t(f"f:{prefix}{i}#member@u{prefix}{i}") for i in range(n)]


def assert_differential(eng, queries):
    ref = ReferenceEngine(eng.manager, eng.config)
    for q in queries:
        got = eng.check_batch([q], max_depth=6)[0]
        want = ref.check_relation_tuple(q, max_depth=6)
        assert got.membership == want.membership, q.to_string()


def base_tuples():
    return ts(
        "f:doc#owner@alice",
        "f:dir#owner@root",
        "f:doc#parent@(f:dir#member)",
        "f:dir#member@bob",
        "f:keep#member@carol",
    )


class TestEngineMerge:
    def test_overflow_merges_instead_of_rebuilding(self):
        eng = make_engine(tuples=base_tuples())
        assert eng.check_batch(
            [t("f:doc#owner@alice")], max_depth=6
        )[0].membership == Membership.IS_MEMBER
        assert eng.stats["snapshot_builds"] == 1

        eng.manager.write_relation_tuples(overflow_writes())
        assert eng.check_batch(
            [t("f:bulk7#member@ubulk7")], max_depth=6
        )[0].membership == Membership.IS_MEMBER
        assert eng.stats.get("incremental_merges", 0) == 1
        assert eng.stats["snapshot_builds"] == 1  # no full rebuild

        assert_differential(eng, ts(
            "f:doc#owner@alice",       # untouched base row
            "f:keep#member@carol",     # untouched base row
            "f:bulk0#member@ubulk0",   # merged insert
            "f:bulk0#member@ubulk1",   # wrong subject
            "f:nope#member@alice",     # absent row
        ))

    def test_merged_deletes_are_tombstones(self):
        eng = make_engine(tuples=base_tuples())
        eng.check_batch([t("f:doc#owner@alice")], max_depth=6)[0]

        eng.manager.delete_relation_tuples(ts("f:doc#owner@alice",
                                              "f:dir#member@bob"))
        eng.manager.write_relation_tuples(overflow_writes())
        assert eng.stats.get("incremental_merges", 0) == 0  # lazy until read
        assert_differential(eng, ts(
            "f:doc#owner@alice",   # deleted plain edge
            "f:dir#member@bob",    # deleted edge behind a CSR row
            "f:doc#view@bob",      # TTU through the mutated row
            "f:keep#member@carol",
        ))
        assert eng.stats.get("incremental_merges", 0) == 1

    def test_merge_with_new_vocab_and_rows(self):
        eng = make_engine(tuples=base_tuples())
        eng.check_batch([t("f:doc#owner@alice")], max_depth=6)[0]

        writes = overflow_writes()
        # new namespace, new objects, new subjects, new subject-set rows
        writes += ts(
            "g:thing#member@newsubj",
            "f:doc#parent@(f:newdir#member)",
            "f:newdir#member@dave",
        )
        eng.manager.write_relation_tuples(writes)
        assert_differential(eng, ts(
            "g:thing#member@newsubj",
            "f:doc#view@dave",        # TTU through the NEW subject-set edge
            "f:doc#view@bob",         # TTU through the OLD edge still works
            "f:doc#view@alice",       # computed rewrite on merged base
        ))
        assert eng.stats.get("incremental_merges", 0) == 1

    def test_delta_overlay_rides_on_merged_base(self):
        eng = make_engine(tuples=base_tuples())
        eng.check_batch([t("f:doc#owner@alice")], max_depth=6)[0]
        eng.manager.write_relation_tuples(overflow_writes())
        eng.check_batch([t("f:bulk0#member@ubulk0")], max_depth=6)[0]
        assert eng.stats.get("incremental_merges", 0) == 1

        # post-merge writes take the normal fixed-shape overlay path
        eng.manager.write_relation_tuples(ts("f:doc#owner@zed"))
        assert eng.check_batch(
            [t("f:doc#owner@zed")], max_depth=6
        )[0].membership == Membership.IS_MEMBER
        assert eng.stats.get("incremental_merges", 0) == 1
        assert eng.stats["snapshot_builds"] == 1

    def test_columnar_store_merge(self):
        """ArrayMap vocabularies (the 1e7-scale tier) merge too."""
        store = ColumnarStore()
        eng = make_engine(store=store, tuples=base_tuples())
        eng.check_batch([t("f:doc#owner@alice")], max_depth=6)[0]
        assert isinstance(eng._state.snapshot.obj_slots, ArrayMap)

        store.write_relation_tuples(overflow_writes())
        store.delete_relation_tuples(ts("f:dir#member@bob"))
        assert_differential(eng, ts(
            "f:bulk3#member@ubulk3",
            "f:dir#member@bob",
            "f:doc#view@bob",
            "f:doc#view@alice",
        ))
        assert eng.stats.get("incremental_merges", 0) == 1
        assert eng.stats["snapshot_builds"] == 1

    def test_randomized_churn_differential(self):
        rng = random.Random(7)
        store = MemoryManager()
        eng = make_engine(store=store, tuples=base_tuples())
        eng.check_batch([t("f:doc#owner@alice")], max_depth=6)[0]

        # universe wide enough that each round's ops stay mostly distinct
        # (the store dedupes idempotent inserts out of the change log)
        objs = [f"o{i}" for i in range(3000)]
        subs = [f"s{i}" for i in range(4)]
        live = set()
        for _round in range(3):
            ops = []
            # extra draws so the DISTINCT op count (the store dedupes
            # repeats out of the log) still exceeds the overlay capacity
            for _ in range(OVERFLOW + 800):
                s = f"f:{rng.choice(objs)}#member@{rng.choice(subs)}"
                if s in live and rng.random() < 0.3:
                    ops.append(("delete", s))
                    live.discard(s)
                else:
                    ops.append(("insert", s))
                    live.add(s)
            for op, s in ops:
                if op == "insert":
                    store.write_relation_tuples([t(s)])
                else:
                    store.delete_relation_tuples([t(s)])
            sample = [
                t(f"f:{rng.choice(objs)}#member@{rng.choice(subs)}")
                for _ in range(64)
            ] + [t(s) for s in rng.sample(sorted(live), 64)]
            ref = ReferenceEngine(eng.manager, eng.config)
            for q, want in zip(
                sample,
                (ref.check_relation_tuple(q, max_depth=6) for q in sample),
            ):
                got = eng.check_batch([q], max_depth=6)[0]
                assert got.membership == want.membership, q.to_string()
        assert eng.stats.get("incremental_merges", 0) >= 2
        assert eng.stats["snapshot_builds"] == 1


class TestMergeGates:
    def test_huge_op_batch_falls_back(self):
        snap = build_snapshot(base_tuples(), NS)
        ops = [("insert", x) for x in overflow_writes(n=70000)]
        assert merge_ops_into_snapshot(snap, ops, version=1) is None

    def test_garbage_threshold_forces_rebuild(self, monkeypatch):
        import keto_tpu.engine.compact as compact

        monkeypatch.setattr(compact, "GARBAGE_FRACTION", 0.0)
        monkeypatch.setattr(compact, "GARBAGE_FLOOR", 0)
        eng = make_engine(tuples=base_tuples())
        eng.check_batch([t("f:doc#owner@alice")], max_depth=6)[0]
        # rewriting an existing CSR row creates garbage > 0 -> gate trips
        writes = overflow_writes() + ts("f:doc#parent@(f:dir2#member)")
        eng.manager.write_relation_tuples(writes)
        eng.check_batch([t("f:bulk0#member@ubulk0")], max_depth=6)[0]
        assert eng.stats.get("incremental_merges", 0) == 0
        assert eng.stats["snapshot_builds"] == 2

    def test_merge_probe_growth_still_exact(self):
        """Dense insertion into one small table grows probe limits; the
        merged snapshot must still answer exactly (recompile, not
        corruption)."""
        base = [t(f"f:base{i}#member@u{i}") for i in range(16)]
        eng = make_engine(tuples=base)
        eng.check_batch([t("f:base0#member@u0")], max_depth=6)[0]
        eng.manager.write_relation_tuples(overflow_writes("dense"))
        assert_differential(eng, [t(f"f:base{i}#member@u{i}") for i in range(16)]
                            + [t(f"f:dense{i}#member@udense{i}")
                               for i in range(0, OVERFLOW, 97)])


class TestArrayMapMerge:
    def test_merged_preserves_existing_ids(self):
        keys = np.array(sorted(["aa", "bb", "cc"]), dtype="U2")
        m = ArrayMap(keys)
        merged = m.merged_with({"ab": 3, "zz": 4})
        assert merged.get("aa") == 0
        assert merged.get("bb") == 1
        assert merged.get("cc") == 2
        assert merged.get("ab") == 3
        assert merged.get("zz") == 4
        assert len(merged) == 5

    def test_longer_keys_widen_dtype(self):
        m = ArrayMap(np.array(["ab"], dtype="U2"))
        merged = m.merged_with({"much-longer-key": 1})
        assert merged.get("much-longer-key") == 1
        assert merged.get("ab") == 0

    def test_bytes_keys(self):
        m = ArrayMap(np.array([b"aa", b"cc"], dtype="S2"))
        merged = m.merged_with({"bb": 2})
        assert merged.get("bb") == 2
        assert merged.get("aa") == 0
        assert merged.get("cc") == 1
        assert merged.keys_by_id_str_array().tolist() == ["aa", "cc", "bb"]

    def test_empty_merge_returns_self(self):
        m = ArrayMap(np.array(["aa"], dtype="U2"))
        assert m.merged_with({}) is m


class TestCheckpointCompat:
    def test_merged_snapshot_checkpoint_roundtrip(self, tmp_path):
        from keto_tpu.engine.checkpoint import load_snapshot, save_snapshot

        eng = make_engine(tuples=base_tuples())
        eng.check_batch([t("f:doc#owner@alice")], max_depth=6)[0]
        eng.manager.write_relation_tuples(overflow_writes())
        eng.check_batch([t("f:bulk0#member@ubulk0")], max_depth=6)[0]
        snap = eng._state.snapshot
        path = str(tmp_path / "m.npz")
        save_snapshot(snap, path)
        loaded = load_snapshot(path)
        assert loaded is not None
        assert loaded.version == snap.version
        assert loaded.n_tuples == snap.n_tuples
        # tombstoned values survive the roundtrip
        assert (loaded.dh_val == np.asarray(snap.dh_val)).all()


class TestExpandStateMerge:
    def test_expand_state_survives_merge(self):
        """The retained full-CSR mirror is PATCHED by the merge (affected
        rows only) — no lazy expand rebuild, and the merged edges are
        served from the device path."""
        from keto_tpu.ketoapi import SubjectSet

        eng = make_engine(tuples=base_tuples())
        tree = eng.expand(SubjectSet("f", "dir", "member"), 3)
        assert {c.tuple.subject_id for c in tree.children} == {"bob"}
        assert eng.stats.get("device_expands", 0) == 1

        writes = overflow_writes() + ts("f:dir#member@zoe")
        eng.manager.write_relation_tuples(writes)
        eng.manager.delete_relation_tuples(ts("f:dir#member@bob"))
        eng.check_batch([t("f:bulk0#member@ubulk0")], max_depth=6)
        assert eng.stats.get("incremental_merges", 0) == 1
        # the merged state still carries a ready expand mirror
        assert eng._state.expand_tables is not None
        assert eng._state.expand_np is not None

        tree2 = eng.expand(SubjectSet("f", "dir", "member"), 3)
        assert {c.tuple.subject_id for c in tree2.children} == {"zoe"}
        # a merged-in row expands on device too (new CSR row at the tail)
        tree3 = eng.expand(SubjectSet("f", "bulk3", "member"), 3)
        assert {c.tuple.subject_id for c in tree3.children} == {"ubulk3"}
        assert eng.stats.get("host_expands", 0) == 0
        assert eng.stats["snapshot_builds"] == 1

    def test_expand_differential_after_merge(self):
        from keto_tpu.ketoapi import SubjectSet

        eng = make_engine(tuples=base_tuples())
        eng.expand(SubjectSet("f", "dir", "member"), 3)
        eng.manager.write_relation_tuples(
            overflow_writes() + ts("f:doc#parent@(f:team#member)",
                                   "f:team#member@tariq")
        )
        eng.check_batch([t("f:bulk0#member@ubulk0")], max_depth=6)
        assert eng.stats.get("incremental_merges", 0) == 1
        ref = ReferenceEngine(eng.manager, eng.config)
        for sub in (SubjectSet("f", "doc", "parent"),
                    SubjectSet("f", "team", "member"),
                    SubjectSet("f", "keep", "member")):
            got = eng.expand(sub, 4)
            want = ref.expand(sub, 4)
            g = {str(c.tuple) for c in (got.children if got else ())}
            w = {str(c.tuple) for c in (want.children if want else ())}
            assert g == w, sub


class TestReverseStateMerge:
    """The transposed mirror (reverse-reachability subsystem) is PATCHED
    by a delta-overflow merge — reverse rows keyed by the changed
    subjects rewrite at the tail via the same patch_csr machinery as the
    forward CSRs — and enumerations stay exactly equal to the oracle
    through interleaved writes and the compaction itself."""

    def test_reverse_state_survives_merge(self):
        eng = make_engine(tuples=base_tuples())
        assert eng.list_objects_batch([("f", "owner", "alice")]) == [["doc"]]
        assert eng._state.reverse_np is not None

        writes = overflow_writes() + ts("f:extra#owner@alice")
        eng.manager.write_relation_tuples(writes)
        eng.manager.delete_relation_tuples(ts("f:doc#owner@alice"))
        assert eng.list_objects_batch([("f", "owner", "alice")]) == [["extra"]]
        assert eng.stats.get("incremental_merges", 0) == 1
        assert eng.stats["snapshot_builds"] == 1  # merged, not rebuilt
        # the merged state still carries a ready (patched) reverse mirror
        assert eng._state.reverse_tables is not None
        assert eng._state.reverse_np is not None
        assert eng._state.reverse_np["garbage"] > 0  # rows were rewritten

        # merged-in rows serve from the DEVICE reverse path (clean base)
        before = eng.stats.get("device_list_objects", 0)
        assert eng.list_objects_batch(
            [("f", "member", "ubulk3")]
        ) == [["bulk3"]]
        assert eng.stats.get("device_list_objects", 0) == before + 1

    def test_reverse_differential_after_merge(self):
        from keto_tpu.engine.reference import ReferenceEngine

        eng = make_engine(tuples=base_tuples())
        eng.list_objects_batch([("f", "owner", "alice")])
        eng.list_subjects_batch([("f", "dir", "member")])
        eng.manager.write_relation_tuples(
            overflow_writes()
            + ts("f:doc2#parent@(f:dir#member)", "f:dir#member@zoe")
        )
        eng.check_batch([t("f:bulk0#member@ubulk0")], max_depth=6)
        assert eng.stats.get("incremental_merges", 0) == 1
        ref = ReferenceEngine(eng.manager, eng.config)
        for sub in ("alice", "bob", "zoe", "ubulk5", "nobody"):
            for rel in ("owner", "member", "view"):
                got = eng.list_objects_batch([("f", rel, sub)])[0]
                want = ref.list_objects("f", rel, sub, 0)
                assert got == want, (sub, rel, got, want)
        for obj in ("doc", "doc2", "dir", "bulk7"):
            for rel in ("member", "view"):
                got = eng.list_subjects_batch([("f", obj, rel)])[0]
                want = ref.list_subjects("f", obj, rel, 0)
                assert got == want, (obj, rel, got, want)
