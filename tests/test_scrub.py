"""Anti-entropy mirror scrubber (engine/scrub.py): device-vs-host
checksum passes, the mirror_corrupt fault differential (detection within
one interval, breaker-degrade auto-repair, zero wrong answers during
degrade vs the host oracle), clean-run zero false positives, and the
/admin/scrub surface."""

import json
import time
import urllib.request

import pytest

from keto_tpu import faults
from keto_tpu.config import Config
from keto_tpu.ketoapi import RelationTuple
from keto_tpu.namespace.ast import ComputedSubjectSet, Relation, SubjectSetRewrite
from keto_tpu.namespace.definitions import Namespace
from keto_tpu.registry import Registry

NAMESPACES = [
    Namespace(
        name="files",
        relations=[
            Relation(name="owner"),
            Relation(
                name="view",
                subject_set_rewrite=SubjectSetRewrite(
                    children=[ComputedSubjectSet(relation="owner")]
                ),
            ),
        ],
    ),
    Namespace(name="groups", relations=[Relation(name="member")]),
]

FIXTURE = [
    "files:a#owner@alice",
    "files:a#view@(files:b#owner)",
    "files:b#owner@bob",
    "groups:g#member@carol",
]
QUERIES = [
    "files:a#owner@alice",
    "files:a#owner@bob",
    "files:a#view@bob",
    "files:a#view@eve",
    "groups:g#member@carol",
]


def ts(*strs):
    return [RelationTuple.from_string(s) for s in strs]


def make_registry(**scrub):
    cfg = Config({"dsn": "memory", "scrub": scrub} if scrub else {"dsn": "memory"})
    cfg.set_namespaces(NAMESPACES)
    reg = Registry(cfg)
    reg.relation_tuple_manager().write_relation_tuples(ts(*FIXTURE))
    return reg


def oracle(reg, q):
    from keto_tpu.engine.reference import ReferenceEngine
    from keto_tpu.storage.definitions import DEFAULT_NETWORK

    ref = ReferenceEngine(reg.relation_tuple_manager(), reg.config)
    return bool(
        ref.check_relation_tuple(
            RelationTuple.from_string(q), 0, DEFAULT_NETWORK
        ).allowed
    )


@pytest.fixture(autouse=True)
def _clear_faults():
    faults.clear()
    yield
    faults.clear()


class TestScrubPass:
    def test_clean_mirror_zero_divergence(self):
        reg = make_registry()
        engine = reg.check_engine()
        assert engine.check_is_member(ts("files:a#view@bob")[0])
        report = reg.mirror_scrubber().scrub_pass()
        assert report["default"]["scrubbed"] is True
        assert report["default"]["diverged"] == []
        assert report["default"]["slices"] > 0

    def test_clean_delta_overlay_state_zero_divergence(self):
        """A state carrying a live delta overlay (and its overlay-
        extended vocab arrays) must also scrub clean — the expectation
        recomputes the overlay, not just the base snapshot."""
        reg = make_registry()
        engine = reg.check_engine()
        engine.check_is_member(ts("files:a#view@bob")[0])
        reg.relation_tuple_manager().write_relation_tuples(
            ts("files:brandnew#owner@dora")
        )
        assert engine.check_is_member(ts("files:brandnew#owner@dora")[0])
        state = engine.mirror_state()
        assert state.has_delta  # the overlay path really is under test
        report = reg.mirror_scrubber().scrub_pass()
        assert report["default"]["diverged"] == []

    def test_unbuilt_engine_not_materialized(self):
        reg = make_registry()
        report = reg.mirror_scrubber().scrub_pass()
        assert report == {}  # built_engines() empty: nothing scrubbed
        assert reg._engine is None

    def test_expectation_cache_pruned_for_vanished_engines(self):
        """The host-side expectation copy dies with its engine — tenant
        churn / invalidation must not grow host memory without bound."""
        reg = make_registry()
        engine = reg.check_engine()
        engine.check_is_member(ts("files:a#owner@alice")[0])
        scrubber = reg.mirror_scrubber()
        scrubber.scrub_pass()
        assert "default" in scrubber._expected
        engine.invalidate()  # state gone: nothing to scrub next pass
        scrubber.scrub_pass()
        assert scrubber._expected == {}

    def test_slice_rows_bounds_chunks(self):
        reg = make_registry(enabled=False, slice_rows=4)
        engine = reg.check_engine()
        engine.check_is_member(ts("files:a#owner@alice")[0])
        scrubber = reg.mirror_scrubber()
        assert scrubber.slice_rows == 4
        report = scrubber.scrub_pass()
        # every table of >4 rows splits into multiple slices
        assert report["default"]["slices"] > report["default"]["tables"]


class TestCorruptionDifferential:
    def test_bitflip_detected_and_auto_repaired(self):
        reg = make_registry()
        engine = reg.check_engine()
        engine.check_is_member(ts("files:a#view@bob")[0])
        key = engine.corrupt_mirror()
        assert key is not None
        scrubber = reg.mirror_scrubber()
        report = scrubber.scrub_pass()
        diverged = report["default"]["diverged"]
        assert diverged and diverged[0]["table"] == key
        # breaker-degrade path engaged + state condemned
        assert reg.circuit_breaker().state == "open"
        assert engine.mirror_state() is None
        # ZERO wrong answers during degrade: every check now matches the
        # host oracle (the rebuild happens on the first check)
        for q in QUERIES:
            assert engine.check_is_member(
                RelationTuple.from_string(q)
            ) == oracle(reg, q)
        # the rebuilt mirror scrubs clean again
        report2 = scrubber.scrub_pass()
        assert report2["default"]["diverged"] == []
        assert scrubber.status()["repairs"] == 1
        m = reg.metrics()
        assert m.scrub_divergence_total.labels(key)._value.get() >= 1
        assert m.scrub_repairs_total._value.get() == 1

    def test_mirror_corrupt_fault_fires_on_submit(self):
        """The mirror_corrupt fault point: one check launch flips a bit,
        the scrubber's next pass catches it (the crash-recovery plane's
        acceptance differential, in-process half)."""
        reg = make_registry()
        engine = reg.check_engine()
        engine.check_batch(ts(QUERIES[0]))  # warm build, clean
        scrubber = reg.mirror_scrubber()
        assert scrubber.scrub_pass()["default"]["diverged"] == []
        spec = faults.set_fault("mirror_corrupt", max_hits=1)
        engine.check_batch(ts(QUERIES[0]))  # fires exactly once
        assert spec.hits == 1
        assert engine.stats.get("mirror_corruptions") == 1
        report = scrubber.scrub_pass()
        assert report["default"]["diverged"]
        # post-repair: answers equal the oracle, mirror scrubs clean
        for q in QUERIES:
            assert engine.check_is_member(
                RelationTuple.from_string(q)
            ) == oracle(reg, q)
        assert scrubber.scrub_pass()["default"]["diverged"] == []

    def test_background_loop_detects_within_interval(self):
        reg = make_registry(enabled=True, interval_s=0.1)
        engine = reg.check_engine()
        engine.check_is_member(ts("files:a#owner@alice")[0])
        scrubber = reg.mirror_scrubber()
        scrubber.start()
        try:
            engine.corrupt_mirror()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if scrubber.status()["repairs"] >= 1:
                    break
                time.sleep(0.02)
            status = scrubber.status()
            assert status["repairs"] >= 1, status
            # the pass that found it completes (passes counts at end)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if scrubber.status()["passes"] >= 1:
                    break
                time.sleep(0.02)
            assert scrubber.status()["passes"] >= 1
        finally:
            scrubber.stop()
        assert scrubber.status()["running"] is False


class TestScrubAdmin:
    def _daemon(self, **scrub):
        from keto_tpu.api.daemon import Daemon

        cfg = Config({
            "dsn": "memory",
            "check": {"engine": "tpu"},
            "scrub": scrub,
            "serve": {
                "read": {"host": "127.0.0.1", "port": 0},
                "write": {"host": "127.0.0.1", "port": 0},
                "metrics": {"host": "127.0.0.1", "port": 0},
            },
        })
        cfg.set_namespaces(NAMESPACES)
        reg = Registry(cfg)
        reg.relation_tuple_manager().write_relation_tuples(ts(*FIXTURE))
        d = Daemon(reg)
        d.start()
        return d

    def test_admin_scrub_status_and_trigger(self):
        d = self._daemon(enabled=False)
        try:
            base = f"http://127.0.0.1:{d.metrics_port}/admin/scrub"
            with urllib.request.urlopen(base, timeout=10) as r:
                status = json.load(r)
            assert status["enabled"] is False and status["passes"] == 0
            # warm the engine so the on-demand pass has a mirror to scrub
            d.registry.check_engine().check_is_member(
                ts("files:a#owner@alice")[0]
            )
            req = urllib.request.Request(base, data=b"", method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                body = json.load(r)
            assert body["passes"] == 1
            assert body["report"]["default"]["scrubbed"] is True
            assert body["report"]["default"]["diverged"] == []
        finally:
            d.stop()

    def test_daemon_starts_and_stops_background_loop(self):
        d = self._daemon(enabled=True, interval_s=0.1)
        try:
            scrubber = d.registry.mirror_scrubber()
            assert scrubber.status()["running"] is True
        finally:
            d.stop()
        assert scrubber.status()["running"] is False
