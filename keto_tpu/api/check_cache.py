"""Snaptoken-consistent serve-side check cache.

Zanzibar attributes its production latency profile to a result/subproblem
cache keyed by evaluation snapshot plus a "lock table" coalescing
concurrent identical checks (paper §3); the reference never shipped
either. This module is the result-cache half (the lock table is the
singleflight dedupe in api/batcher.py): positive AND negative Check
verdicts cached at the store version they were computed at, served by the
transports BEFORE the batcher so a hit skips assemble/dispatch/device
entirely.

Correctness contract — a hit is *provably* as fresh as an uncached ride
at the same snaptoken:

  - Every entry records the store version its answer is authoritative
    at. Device-path answers carry the evaluated engine state's
    `covered_version` (plumbed through `check_batch_resolve_v`); answers
    without a plumbed version (host engine, host-replayed riders) are
    stored only when a re-read of the store version equals the
    enforce-time version — i.e. no write raced the evaluation, so the
    answer is exactly the enforce-version answer.
  - A lookup provides the request's enforce-time store version (the
    value the response snaptoken is minted from — the transports already
    read it per request in `enforce_snaptoken`). A hit requires
    `entry.version == version`: the served bytes, snaptoken included,
    are identical to what a cache-miss evaluation at that version
    returns. No time-travel, no stale reads — any write bumps the store
    version and version-mismatched entries stop hitting at once, with
    no dependence on invalidation delivery latency.
  - A namespace-config change alters answers WITHOUT a store-version
    bump, so entries are additionally gated on the namespace manager's
    `config_generation` (bumped on set/hot-reload); a generation change
    flushes the cache.

Invalidation (hygiene + memory, never load-bearing for correctness):
WatchHub commit events poke `notify_commit(nid)`; a background thread
reads the store changelog since the last pass and precisely deletes the
entries a changed tuple can directly flip — the entry for the changed
node row (namespace, object, relation) and every entry whose subject
matches the changed tuple's subject, the same two key families the delta
overlay's reverse-dirty (rd_*) table tracks for the reverse kernel.
Entries invalidated only transitively (an interior edge two hops up) are
not enumerable without a reverse closure; they die to the version gate
and age out of the LRU.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

DEFAULT_MAX_ENTRIES = 65536


def _fastpath_begin(cache, nid, t, max_depth, version, rt):
    """Shared pre-evaluation half of the serve fast path: (cached
    result | None, captured config generation | None). The generation
    is captured BEFORE evaluating a miss — like the enforce-time store
    version, it pins what the verdict was computed under; a hot-reload
    racing the evaluation then makes store() skip instead of caching an
    old-config answer under the new generation."""
    if cache is None:
        return None, None
    res = cache.lookup(nid, t, max_depth, version, rt=rt)
    if res is not None:
        return res, None
    return None, cache.generation()


def require_answer_floor(computed_v, version) -> None:
    """The store-outage no-time-travel backstop: an answer pinned to a
    version OLDER than the request's enforce-time version would ship
    under a snaptoken that overstates its freshness. Impossible while
    the store is healthy (the engine syncs to >= the enforce-time read
    before evaluating); reachable only when the store dies between the
    transport's version read and the engine's — then the typed 503
    wins over a stale-claiming answer."""
    if computed_v is not None and version is not None and computed_v < version:
        from ..errors import StoreUnavailableError

        raise StoreUnavailableError(
            f"store became unavailable mid-request: the answer is "
            f"pinned to v{computed_v} but the response snaptoken was "
            f"minted at v{version}",
            breaker_open=True,
        )


def _record_workload(registry, nid, t, res, rt) -> None:
    """Per-(nid, relation) accounting feed: every SINGLE check that
    clears the serve gate — cache hit or evaluated — lands one sample
    in the workload observatory (verdict mix, answering-tier mix,
    hot-key sketches). Errored results are the transport's problem (it
    raises them into a status code; the SLO availability track counts
    them at finish_request_telemetry) — they carry no verdict, so the
    accounting skips them. Never raises: observability must not be
    able to fail a Check."""
    try:
        obs = registry.workload_observatory()
        if obs is not None and res.error is None:
            obs.record_check(
                nid, t, res.allowed, tier=getattr(rt, "tier", None)
            )
    # ketolint: allow[typed-error] reason=observability isolation on the serve fast path: an accounting bug must degrade to a lost sample, never replace the computed verdict the client is owed
    except Exception:  # pragma: no cover - defensive isolation
        pass


def cached_check(registry, batcher, nid, t, max_depth, version, rt):
    """The transports' shared serve fast path: consult the cache, ride
    the batcher (or the bare engine) on a miss, store the verdict.
    Returns the CheckResult (error still attached — the transport maps
    it). REST and sync-gRPC call this; the aio plane awaits
    cached_check_async — both halves of the gate live here."""
    cache = registry.check_cache()
    res, gen = _fastpath_begin(cache, nid, t, max_depth, version, rt)
    if res is not None:
        _record_workload(registry, nid, t, res, rt)
        return res
    if batcher is not None:
        res, computed_v = batcher.check_versioned(t, max_depth, nid=nid, rt=rt)
    else:
        res = registry.check_engine(nid).check_relation_tuple(t, max_depth)
        computed_v = None
    require_answer_floor(computed_v, version)
    if cache is not None:
        cache.store(nid, t, max_depth, res, computed_v, version, gen=gen)
    _record_workload(registry, nid, t, res, rt)
    return res


async def cached_check_async(registry, batcher, nid, t, max_depth, version, rt):
    """cached_check's aio twin (the batcher call is awaited; everything
    else is the same gate, shared via _fastpath_begin/store)."""
    cache = registry.check_cache()
    res, gen = _fastpath_begin(cache, nid, t, max_depth, version, rt)
    if res is not None:
        _record_workload(registry, nid, t, res, rt)
        return res
    res, computed_v = await batcher.check_versioned(t, max_depth, nid=nid, rt=rt)
    require_answer_floor(computed_v, version)
    if cache is not None:
        cache.store(nid, t, max_depth, res, computed_v, version, gen=gen)
    _record_workload(registry, nid, t, res, rt)
    return res


class _Entry:
    __slots__ = ("result", "version", "expires")

    def __init__(self, result, version: int, expires: float):
        self.result = result
        self.version = version
        self.expires = expires


def _key_for(nid: str, t, max_depth: int) -> tuple:
    # field-structured (not the display string, which is not injective);
    # same shape as the engine's host-replay memo key
    return (
        nid, t.namespace, t.object, t.relation,
        t.subject_id, t.subject_set, max_depth,
    )


class CheckCache:
    """Versioned (nid, object, relation, subject, max_depth) -> verdict
    LRU with precise commit-driven invalidation. Thread-safe; the hot
    path is one lock + two dict operations."""

    def __init__(
        self,
        manager,
        config,
        max_entries: int = DEFAULT_MAX_ENTRIES,
        ttl_s: float = 0.0,
        metrics=None,
    ):
        self._manager = manager
        self._config = config
        self.max_entries = max(int(max_entries), 1)
        self.ttl_s = float(ttl_s or 0.0)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        # precise-invalidation indexes: the two key families a changed
        # tuple can directly flip (the rd_* derivation in engine/delta.py)
        self._by_node: dict[tuple, set] = {}
        self._by_subject: dict[tuple, set] = {}
        self._by_nid: dict[str, set] = {}
        self._cfg_gen = None
        # invalidation plane (lazy thread, engine push-refresh pattern)
        self._inval_mu = threading.Lock()
        self._inval_event: Optional[threading.Event] = None
        self._inval_versions: dict[str, int] = {}
        self._pending_nids: set[str] = set()
        self._closed = False
        # local mirrors of the metric counters (bench/tools read these
        # without scraping; also keeps the module usable metrics-less)
        self.counts = {"hit": 0, "miss": 0, "stale": 0, "invalidation": 0}
        if metrics is not None:
            ops = metrics.check_cache_ops
            self._c = {op: ops.labels(op) for op in self.counts}
            self._entries_gauge = metrics.check_cache_entries
        else:
            self._c = None
            self._entries_gauge = None

    # -- bookkeeping -----------------------------------------------------------

    def _count(self, op: str, n: int = 1) -> None:
        self.counts[op] += n
        if self._c is not None:
            self._c[op].inc(n)

    def _set_gauge_locked(self) -> None:
        if self._entries_gauge is not None:
            self._entries_gauge.set(len(self._entries))

    def _generation(self):
        nm = self._config.namespace_manager()
        gen = getattr(nm, "config_generation", None)
        return gen if gen is not None else id(nm)

    def generation(self):
        """The current namespace-config generation token — capture it
        BEFORE evaluating a miss and hand it to store(), so a config
        hot-reload racing the evaluation cannot cache an old-config
        verdict under the new generation."""
        return self._generation()

    def _check_generation_locked(self, gen) -> None:
        if gen != self._cfg_gen:
            self._entries.clear()
            self._by_node.clear()
            self._by_subject.clear()
            self._by_nid.clear()
            self._cfg_gen = gen

    # -- hot path --------------------------------------------------------------

    def lookup(self, nid: str, t, max_depth: int, version: int, rt=None):
        """The fast-path probe: the cached CheckResult iff an entry for
        this exact query is authoritative at exactly `version` (the
        request's enforce-time store version — the value its response
        snaptoken is minted from). On a hit the lookup duration lands on
        the request's trace as the `cache` stage; a hit request records
        NO assemble/dispatch/device_wait time because those stages never
        run."""
        t0 = time.perf_counter()
        key = _key_for(nid, t, max_depth)
        gen = self._generation()
        with self._lock:
            self._check_generation_locked(gen)
            e = self._entries.get(key)
            if e is not None and self.ttl_s and time.monotonic() > e.expires:
                self._drop_locked(key)
                self._set_gauge_locked()
                e = None
            if e is None:
                self._count("miss")
                return None
            if e.version != version:
                if e.version < version:
                    # provably dead: the store moved past it
                    self._drop_locked(key)
                    self._set_gauge_locked()
                    self._count("stale")
                else:
                    # entry NEWER than the request's enforce version (a
                    # write + re-store raced this lookup): not stale by
                    # the metric's definition — there is simply no entry
                    # at the demanded version
                    self._count("miss")
                return None
            self._entries.move_to_end(key)
            # counted under the lock: self.counts is a plain dict and
            # concurrent hot-key hits would lose increments otherwise
            self._count("hit")
        dur = time.perf_counter() - t0
        if rt is not None:
            rt.add_stage("cache", dur)
            rt.tier = "cache"
        if self.metrics is not None:
            self.metrics.observe_stage(
                "cache", dur,
                trace_id=rt.ctx.trace_id if rt is not None else None,
            )
        return e.result

    def store(
        self,
        nid: str,
        t,
        max_depth: int,
        result,
        computed_version: Optional[int],
        enforce_version: int,
        gen=None,
    ) -> None:
        """Record one evaluated verdict. `computed_version` is the store
        version the engine pinned the answer to (state.covered_version,
        via check_batch_resolve_v) or None when the evaluation path
        cannot pin one (host engine, host-replayed rider): then the
        answer is cacheable only if the store version has not moved
        since enforce time — one re-read decides, and a raced write
        simply skips the store (the next identical miss re-populates).
        `gen` is the config generation captured BEFORE evaluation
        (generation()); a mismatch with the current generation means a
        namespace hot-reload raced the evaluation, so the verdict —
        computed under the OLD config — must not enter the flushed
        cache."""
        if result is None or getattr(result, "error", None) is not None:
            return
        version = computed_version
        if version is None:
            from ..errors import StoreUnavailableError

            try:
                current = self._manager.version(nid=nid)
            except StoreUnavailableError:
                # store outage: the raced-write re-check cannot run, so
                # the unpinned answer is simply not cached (the caller
                # already has it; caching is an optimization)
                return
            if current != enforce_version:
                return
            version = enforce_version
        key = _key_for(nid, t, max_depth)
        current_gen = self._generation()
        if gen is not None and gen != current_gen:
            return
        gen = current_gen
        expires = time.monotonic() + self.ttl_s if self.ttl_s else 0.0
        node_k = (nid, t.namespace, t.object, t.relation)
        subj_k = (nid, t.subject_id, t.subject_set)
        with self._lock:
            self._check_generation_locked(gen)
            old = self._entries.get(key)
            if old is not None:
                if old.version > version:
                    return  # never downgrade a fresher entry
                if old.version == version:
                    # singleflight fan-out: every rider re-stores the
                    # identical verdict — recency bump only, skip the
                    # redundant index writes
                    self._entries.move_to_end(key)
                    return
            self._entries[key] = _Entry(result, version, expires)
            self._entries.move_to_end(key)
            self._by_node.setdefault(node_k, set()).add(key)
            self._by_subject.setdefault(subj_k, set()).add(key)
            self._by_nid.setdefault(nid, set()).add(key)
            while len(self._entries) > self.max_entries:
                evicted, _ = self._entries.popitem(last=False)
                self._unindex_locked(evicted)
            self._set_gauge_locked()

    # -- entry removal (caller holds self._lock) -------------------------------

    def _unindex_locked(self, key: tuple) -> None:
        nid, ns, obj, rel, sid, sset, _depth = key
        for index, k in (
            (self._by_node, (nid, ns, obj, rel)),
            (self._by_subject, (nid, sid, sset)),
            (self._by_nid, nid),
        ):
            s = index.get(k)
            if s is not None:
                s.discard(key)
                if not s:
                    del index[k]

    def _drop_locked(self, key: tuple) -> None:
        if self._entries.pop(key, None) is not None:
            self._unindex_locked(key)

    # -- invalidation plane ----------------------------------------------------

    def notify_commit(self, nid: str) -> None:
        """WatchHub commit listener (via the registry): runs on the
        writer thread, so it only flags the nid and wakes the
        invalidation thread — bursts of writes coalesce into one pass.
        Correctness never waits on this: the version gate in lookup()
        already stopped serving pre-commit entries the moment the store
        version moved."""
        if self._closed:
            return
        ev = self._inval_event
        if ev is None:
            with self._inval_mu:
                ev = self._inval_event
                if ev is None:
                    ev = threading.Event()
                    thread = threading.Thread(
                        target=self._invalidate_loop,
                        args=(ev,),
                        name="keto-check-cache-invalidate",
                        daemon=True,
                    )
                    self._inval_event = ev
                    thread.start()
        with self._inval_mu:
            self._pending_nids.add(nid)
        ev.set()

    def _invalidate_loop(self, ev: threading.Event) -> None:
        while True:
            ev.wait()
            if self._closed:
                return
            ev.clear()
            with self._inval_mu:
                nids, self._pending_nids = self._pending_nids, set()
            for nid in nids:
                try:
                    self._invalidate_nid(nid)
                except Exception:  # noqa: BLE001 — hygiene thread must
                    # never die; the version gate carries correctness
                    import logging

                    logging.getLogger("keto_tpu").debug(
                        "check-cache invalidation pass failed", exc_info=True
                    )

    # drop batch size per lock hold: invalidation passes must not stall
    # concurrent lookups (the aio plane runs lookup in-loop) for the
    # length of a 65536-entry sweep
    _DROP_CHUNK = 256

    def _drop_chunked(self, keys, keep=None) -> int:
        """Drop `keys` in small locked chunks so hot-path lookups
        interleave with a long invalidation sweep; `keep(entry)` retains
        matching entries. Returns the number removed."""
        removed = 0
        keys = list(keys)
        for i in range(0, len(keys), self._DROP_CHUNK):
            with self._lock:
                for key in keys[i : i + self._DROP_CHUNK]:
                    e = self._entries.get(key)
                    if e is None or (keep is not None and keep(e)):
                        continue
                    self._drop_locked(key)
                    removed += 1
                self._set_gauge_locked()
        return removed

    def _invalidate_nid(self, nid: str) -> None:
        since = self._inval_versions.get(nid)
        current = self._manager.version(nid=nid)
        removed = 0
        if since is None:
            # first pass for this nid: no changelog floor yet — sweep
            # entries the store has provably moved past
            with self._lock:
                keys = list(self._by_nid.get(nid, ()))
            removed = self._drop_chunked(
                keys, keep=lambda e: e.version >= current
            )
        else:
            changelog = getattr(self._manager, "changelog_since", None)
            ops = changelog(since, nid=nid) if changelog is not None else None
            if ops is None:
                # unreachable gap (trimmed log / bulk load): conservative
                # whole-nid drop
                with self._lock:
                    keys = list(self._by_nid.get(nid, ()))
                removed = self._drop_chunked(keys)
            else:
                # precise pass: collect the directly-flippable keys (the
                # rd_* families) under short lock holds — the ops list
                # can be a whole migration's worth, so the scan is
                # chunked like the drops
                doomed: set = set()
                ops = list(ops)
                for i in range(0, len(ops), self._DROP_CHUNK):
                    with self._lock:
                        for _v, _op, t in ops[i : i + self._DROP_CHUNK]:
                            doomed.update(
                                self._by_node.get(
                                    (nid, t.namespace, t.object, t.relation),
                                    (),
                                )
                            )
                            doomed.update(
                                self._by_subject.get(
                                    (nid, t.subject_id, t.subject_set), ()
                                )
                            )
                removed = self._drop_chunked(doomed)
        self._inval_versions[nid] = current
        if removed:
            with self._lock:
                self._count("invalidation", removed)

    # -- lifecycle / introspection ---------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = dict(self.counts)
            out["entries"] = len(self._entries)
        total = out["hit"] + out["miss"] + out["stale"]
        out["hit_ratio"] = round(out["hit"] / total, 4) if total else 0.0
        return out

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_node.clear()
            self._by_subject.clear()
            self._by_nid.clear()
            self._set_gauge_locked()

    def close(self) -> None:
        self._closed = True
        ev = self._inval_event
        if ev is not None:
            ev.set()
