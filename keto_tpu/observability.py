"""Observability: Prometheus metrics, OpenTelemetry tracing, request logs.

Parity with the reference's aux subsystems (SURVEY.md §5.1/§5.5):
prometheusx metrics served on the metrics port (registry_default.go:
131-143, daemon.go:421-436), otelx tracer with spans in every persister/
handler method, logrusx structured request logging (daemon.go:294).

Beyond parity, this module carries the request-scoped telemetry plane:
W3C `traceparent` contexts ingested at the transports flow (as a
`RequestTrace`) through the batcher into the engine, so one Check yields
correlated spans for transport handling, batcher queue wait, batch
assembly/padding, device dispatch, device wait, and host-fallback replay
— and the same stage breakdown lands in the `check_stage_duration`
histogram, the structured request log, and the threshold-configurable
slow-query log (`log.slow_query_ms`).

Everything here degrades gracefully: metrics use a dedicated
CollectorRegistry (so embedders/tests never hit duplicate-collector
errors), and tracing is a no-op unless `tracing.enabled` is set.

The §5m export plane rides the same machinery: setting
`observability.otlp.endpoint` turns the tracer into an exporting
recorder — completed spans leave the process as OTLP/HTTP-JSON through
the bounded, never-blocking SpanExporter, transport spans anchor the
trace as parent-linked roots, engine stage spans carry flight-recorder
launch ids as span events, and the check-stage histogram attaches
trace_id exemplars served via OpenMetrics content negotiation.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import secrets
import threading
import time
from typing import Optional

import prometheus_client as prom

logger = logging.getLogger("keto_tpu")

# the canonical stage vocabulary, transport to silicon; every stage name
# used with Metrics.observe_stage / RequestTrace.add_stage comes from
# here so the docs table and the bench summary can enumerate them
CHECK_STAGES = (
    "transport",      # handler time outside the batcher/engine stages
    "cache",          # check-cache fast-path lookup (hits only: a hit
                      # request records NO assemble/dispatch/device_wait
                      # because those stages never run)
    "queue",          # batcher queue wait (enqueue -> group dispatch)
    "assemble",       # state refresh + batch encoding + bucket padding
    "dispatch",       # device launch (H2D upload + async kernel dispatch)
    "device_wait",    # block-until-ready + readback + unpack
    "host_fallback",  # exact host replay of cause-flagged queries
)


# -- W3C trace context --------------------------------------------------------


class SpanContext:
    """One W3C trace-context vertex: (trace_id, span_id). `child()` mints
    a new span id under the same trace — the propagation primitive.
    `parent_span_id` remembers the span this one was minted under (the
    caller's span id for a context ingested from `traceparent`): the
    OTLP exporter needs it so the transport ROOT span can parent-link to
    the caller's client span instead of dangling."""

    __slots__ = ("trace_id", "span_id", "sampled", "parent_span_id")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True,
                 parent_span_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled
        self.parent_span_id = parent_span_id

    def child(self) -> "SpanContext":
        return SpanContext(
            self.trace_id, secrets.token_hex(8), self.sampled,
            parent_span_id=self.span_id,
        )

    def to_traceparent(self) -> str:
        return (
            f"00-{self.trace_id}-{self.span_id}-"
            f"{'01' if self.sampled else '00'}"
        )


def new_trace() -> SpanContext:
    return SpanContext(secrets.token_hex(16), secrets.token_hex(8))


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """Parse a W3C `traceparent` header/metadata value; None for absent
    or malformed input (a bad header must never fail the request — the
    spec says restart the trace)."""
    if not value:
        return None
    parts = value.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or version == "ff":
        return None
    if len(trace_id) != 32 or len(span_id) != 16 or len(flags) != 2:
        return None
    try:
        if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
            return None
        sampled = bool(int(flags, 16) & 1)
    except ValueError:
        return None
    return SpanContext(trace_id, span_id, sampled)


class RequestTrace:
    """Per-request telemetry carrier: the span context plus accumulated
    per-stage seconds. Created at the transport, handed through the
    batcher into the engine; every layer adds its stage durations.
    `deadline` (resilience.Deadline | None) rides the same handoff so
    every stage boundary can fail the request fast once the end-to-end
    budget is spent — the Zanzibar deadline-scoped-evaluation carrier.
    `launch_ids` collects the flight-recorder launch ids of every device
    batch this request rode (normally one; multi-split batches append
    several), so a slow-query line or request log joins its exact
    launch record in `GET /admin/flightrec`.
    `tier` is the ANSWERING tier (cache | closure | device | host |
    vocab), stamped by whichever layer produced the verdict — the check
    cache on a hit, the engine resolve paths beside their explain-sink
    fills, the REST unknown-namespace corner — so the request log and
    the workload observatory see the tier on EVERY check, not just
    explain=true ones."""

    __slots__ = (
        "ctx", "stages", "deadline", "launch_ids", "min_version", "tier",
    )

    def __init__(self, ctx: Optional[SpanContext] = None, deadline=None):
        self.ctx = ctx if ctx is not None else new_trace()
        self.stages: dict[str, float] = {}
        self.deadline = deadline
        self.launch_ids: list[int] = []
        # answering tier, stamped by the layer that produced the verdict
        self.tier: Optional[str] = None
        # the store version this request's response snaptoken is minted
        # at, stamped by snaptoken enforcement: the store-outage
        # degradation plane's no-time-travel floor — a degraded (mirror)
        # answer below this version must 503, never serve (the token
        # would overstate the answer's freshness)
        self.min_version: Optional[int] = None

    def add_stage(self, name: str, seconds: float) -> None:
        self.stages[name] = self.stages.get(name, 0.0) + seconds


# current request telemetry for the executing handler; transports set it
# so nested layers (traced store ops, engine spans on the same thread)
# correlate without threading an argument through every signature
CURRENT_TRACE: contextvars.ContextVar[Optional[RequestTrace]] = (
    contextvars.ContextVar("keto_tpu_request_trace", default=None)
)


def set_request_trace(rt: Optional[RequestTrace]):
    return CURRENT_TRACE.set(rt)


def reset_request_trace(token) -> None:
    CURRENT_TRACE.reset(token)


def current_request_trace() -> Optional[RequestTrace]:
    return CURRENT_TRACE.get()


# -- flight recorder -----------------------------------------------------------

# process-wide monotonically increasing launch ids: unique across every
# engine/plane in the process so one id joins the slow-query log, the
# typed batch-failure error, and the ring entry unambiguously
_launch_id_lock = threading.Lock()
_launch_id_next = 0


def next_launch_id() -> int:
    """Allocate one launch id (ids advance even when recording is
    disabled — logs and errors still need a stable correlation key)."""
    global _launch_id_next
    with _launch_id_lock:
        _launch_id_next += 1
        return _launch_id_next


class FlightRecorder:
    """Bounded per-process ring of per-launch device introspection
    entries — the serving plane's black-box recorder.

    One entry per device launch (check batches; expand and reverse
    launches record too), written at the launch's EXISTING resolve-phase
    sync point from counters the kernel accumulated on device
    (engine/kernel.py STAT_*): loop iterations used vs cap, frontier
    occupancy (sum/max/live), probe hits, candidate rows gathered,
    estimated gather bytes, batch occupancy real/padded, host-replay
    causes, per-stage seconds, and the riders' trace ids. Context
    providers (registry-wired: breaker state, armed faults) stamp every
    entry with ambient device-path health.

    `dump()` is the failure path's escape hatch: the batchers call it on
    device-batch failure / watchdog abandon so the last launches' records
    land in the log before the evidence scrolls out of the ring; the
    metrics listener serves the live ring at `GET /admin/flightrec`.

    Thread-safe; recording is O(1) appends onto a deque. Entries carry
    `t_mono` (time.monotonic at resolve) — wall-clock stamps are banned
    repo-wide (ketolint clock-monotonic); readers compute ages against
    the monotonic clock they already hold."""

    DUMP_TAIL = 16  # entries logged per dump (the full ring would spam)

    def __init__(self, enabled: bool = True, capacity: int = 256,
                 metrics=None):
        import collections

        self.enabled = bool(enabled)
        self.capacity = max(int(capacity), 1)
        self.metrics = metrics
        self._ring = collections.deque(maxlen=self.capacity)
        self._mu = threading.Lock()
        # () -> dict merged into every entry; registered by the registry
        # (breaker state, armed faults). Called OUTSIDE the ring lock.
        self.context_providers: list = []

    def record(self, entry: dict) -> None:
        if not self.enabled:
            return
        for provider in self.context_providers:
            try:
                entry.update(provider())
            except Exception:  # a broken provider must never fail a launch
                logger.debug("flightrec context provider failed", exc_info=True)
        entry.setdefault("t_mono", time.monotonic())
        with self._mu:
            self._ring.append(entry)

    def entries(self) -> list[dict]:
        with self._mu:
            return list(self._ring)

    def dump(self, reason: str) -> list[dict]:
        """Auto-dump on batch failure / watchdog abandon: log the tail of
        the ring as one structured WARNING (the entries most likely to
        explain the failure) and count the dump. Returns the full ring
        for programmatic callers (smoke tools, tests). Disabled recorder:
        silent no-op — an empty-tail WARNING per batch failure is noise
        with zero evidence (batch-failed counters already count those)."""
        if not self.enabled:
            return []
        entries = self.entries()
        if self.metrics is not None:
            self.metrics.flightrec_dumps_total.labels(reason).inc()
        tail = entries[-self.DUMP_TAIL:]
        logger.warning(
            "flight recorder dump reason=%s entries=%d tail=%s",
            reason, len(entries), tail,
        )
        return entries


def summarize_launches(entries: list[dict], kind: str = "check") -> dict:
    """Per-leg aggregates of flight-recorder entries — the BENCH/SCALE
    json's launch-telemetry record (mean/p95 iterations, gather bytes
    per check, padding waste). Schema pinned by the bench golden test;
    returns {} for an empty window so legs without launches stay absent
    from the json instead of recording degenerate zeros. `kind` selects
    the launch family (the closure-on deep leg summarizes its
    single-step `closure` launches instead of BFS `check` ones)."""
    checks = [e for e in entries if e.get("kind") == kind]
    if not checks:
        return {}

    def _vals(key):
        return [float(e.get(key, 0)) for e in checks]

    def _p95(vals):
        s = sorted(vals)
        return s[min(int(0.95 * (len(s) - 1) + 0.5), len(s) - 1)]

    iters = _vals("steps")
    waste = [1.0 - float(e.get("occupancy", 1.0)) for e in checks]
    n_checks = sum(int(e.get("n", 0)) for e in checks) or 1
    return {
        "launches": len(checks),
        "iterations_mean": round(sum(iters) / len(iters), 2),
        "iterations_p95": round(_p95(iters), 2),
        "step_cap": int(max(e.get("step_cap", 0) for e in checks)),
        "frontier_peak_max": int(max(e.get("frontier_max", 0) for e in checks)),
        "live_task_steps_mean": round(
            sum(_vals("live_sum")) / len(checks), 1
        ),
        "gather_bytes_per_check": round(
            sum(_vals("gather_bytes_est")) / n_checks, 1
        ),
        "edge_rows_per_check": round(sum(_vals("edge_rows")) / n_checks, 3),
        "padding_waste_mean": round(sum(waste) / len(waste), 4),
    }


class Metrics:
    """Prometheus metrics for the serving path + the TPU engine."""

    def __init__(self):
        self.registry = prom.CollectorRegistry()
        self.requests_total = prom.Counter(
            "keto_tpu_requests_total",
            "RPC/REST requests served",
            ["transport", "method", "code"],
            registry=self.registry,
        )
        self.request_duration = prom.Histogram(
            "keto_tpu_request_duration_seconds",
            "Request latency",
            ["transport", "method"],
            registry=self.registry,
            buckets=(
                0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
            ),
        )
        self.checks_total = prom.Counter(
            "keto_tpu_checks_total",
            "Check() queries evaluated, by engine path",
            ["path"],  # device | host
            registry=self.registry,
        )
        self.host_fallback_total = prom.Counter(
            "keto_tpu_host_fallback_total",
            "Check() queries replayed on the exact host engine, by kernel "
            "cause code (engine/kernel.py CAUSE_*) — distinguishes "
            "capacity cliffs (island_overflow, frontier_overflow, "
            "rewrite_cap) from semantic causes (relation_not_found, "
            "config_missing) and staleness (dirty_row)",
            ["cause"],
            registry=self.registry,
        )
        self.check_batch_size = prom.Histogram(
            "keto_tpu_check_batch_size",
            "Queries per device batch",
            registry=self.registry,
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096),
        )
        self.snapshot_builds_total = prom.Counter(
            "keto_tpu_snapshot_builds_total",
            "Device graph-mirror rebuilds",
            registry=self.registry,
        )
        self.snapshot_tuples = prom.Gauge(
            "keto_tpu_snapshot_tuples",
            "Relation tuples in the current device snapshot",
            registry=self.registry,
        )
        self.snapshot_build_duration = prom.Histogram(
            "keto_tpu_snapshot_build_duration_seconds",
            "Device graph-mirror rebuild latency",
            registry=self.registry,
        )
        # watch subsystem (keto_tpu/watch): changelog streaming health
        self.watch_streams_active = prom.Gauge(
            "keto_tpu_watch_streams_active",
            "Open watch subscriptions (gRPC streams + SSE connections)",
            registry=self.registry,
        )
        self.watch_events_delivered_total = prom.Counter(
            "keto_tpu_watch_events_delivered_total",
            "Tuple changes delivered to watch subscribers (counts "
            "individual insert/delete changes, summed over subscribers)",
            registry=self.registry,
        )
        self.watch_resets_total = prom.Counter(
            "keto_tpu_watch_resets_total",
            "RESET events handed to watch subscribers (ring-buffer "
            "overflow, trimmed changelog, bulk load) — every gap is "
            "explicit, never a silent drop",
            registry=self.registry,
        )
        self.watch_lag_seconds = prom.Gauge(
            "keto_tpu_watch_lag_seconds",
            "Delay between the oldest undelivered commit's write hook "
            "and its fan-out to subscribers (watch hub tail lag)",
            registry=self.registry,
        )
        self.watch_heartbeats_total = prom.Counter(
            "keto_tpu_watch_heartbeats_total",
            "In-band HEARTBEAT frames broadcast on idle watch streams "
            "(opt-in via watch.heartbeat_s) — the liveness signal an "
            "out-of-process follower tail uses to tell a quiet upstream "
            "from a dead one; emitted through store outages too",
            registry=self.registry,
        )
        # request-scoped telemetry plane: the per-stage Check breakdown
        # (CHECK_STAGES) — one observation per stage per device batch
        # (batch-shared stages are observed once, not per rider), so a
        # p95 regression attributes to queue wait vs padding vs dispatch
        # vs device wait vs host replay instead of one flat duration
        self.check_stage_duration = prom.Histogram(
            "keto_tpu_check_stage_duration_seconds",
            "Check serving time per pipeline stage (transport | cache | "
            "queue | assemble | dispatch | device_wait | host_fallback); "
            "batch-level stages observe once per device batch; `cache` "
            "observes per cache hit (hit requests record no "
            "assemble/dispatch/device_wait time)",
            ["stage"],
            registry=self.registry,
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 1.0,
            ),
        )
        self.batcher_queue_depth = prom.Gauge(
            "keto_tpu_batcher_queue_depth",
            "Requests waiting in a check-batcher queue, sampled at "
            "enqueue/drain; `plane` separates the threaded batcher from "
            "the aio one (both can serve simultaneously — an unlabeled "
            "gauge would be last-writer-wins between them)",
            ["plane"],  # threaded | aio
            registry=self.registry,
        )
        self.inflight_launches = prom.Gauge(
            "keto_tpu_inflight_launches",
            "Launched-but-unresolved device batches (bounded by the "
            "batcher's in-flight semaphore)",
            registry=self.registry,
        )
        self.batch_occupancy = prom.Gauge(
            "keto_tpu_batch_occupancy",
            "Real rows / padded bucket rows of the most recent device "
            "batch (1.0 = no padding waste)",
            registry=self.registry,
        )
        self.delta_overlay_ops = prom.Gauge(
            "keto_tpu_delta_overlay_ops",
            "Pending store ops compiled into the current delta overlay "
            "(0 after a compaction/rebuild; compaction forces at "
            "DELTA_COMPACT_THRESHOLD)",
            registry=self.registry,
        )
        self.snapshot_hbm_bytes = prom.Gauge(
            "keto_tpu_snapshot_hbm_bytes",
            "Device bytes held by the current check-table mirror "
            "(packed edge/rewrite/delta tables; expand/reverse extras "
            "not included)",
            registry=self.registry,
        )
        self.compaction_lag_versions = prom.Gauge(
            "keto_tpu_compaction_lag_versions",
            "Store commits folded into the delta overlay since the base "
            "snapshot (covered_version - base_version): distance toward "
            "the next compaction",
            registry=self.registry,
        )
        self.refresh_lag_seconds = prom.Gauge(
            "keto_tpu_refresh_lag_seconds",
            "Push-refresher lag: seconds from the triggering commit's "
            "write hook to delta-overlay fold completion (last refresh)",
            registry=self.registry,
        )
        # snaptoken-consistent serve-side check cache (api/check_cache.py)
        self.check_cache_ops = prom.Counter(
            "keto_tpu_check_cache_ops_total",
            "Check-cache outcomes: hit (served before the batcher — no "
            "assemble/dispatch/device stages run), miss (no entry), "
            "stale (entry pinned to an older store version than the "
            "request's), invalidation (entries removed by commit-driven "
            "precise invalidation)",
            ["op"],  # hit | miss | stale | invalidation
            registry=self.registry,
        )
        self.check_cache_entries = prom.Gauge(
            "keto_tpu_check_cache_entries",
            "Entries currently held by the serve-side check cache "
            "(bounded by check.cache.max_entries, LRU-evicted)",
            registry=self.registry,
        )
        self.check_coalesced_total = prom.Counter(
            "keto_tpu_check_coalesced_total",
            "Concurrent identical pending checks collapsed onto one "
            "in-flight batch slot and fanned back out (singleflight "
            "dedupe, Zanzibar's hot-spot lock table)",
            registry=self.registry,
        )
        # overload & failure resilience plane (keto_tpu/resilience.py):
        # deadlines, admission control, device-path circuit breaker
        self.deadline_exceeded_total = prom.Counter(
            "keto_tpu_deadline_exceeded_total",
            "Checks failed with a typed DEADLINE_EXCEEDED (REST 504), by "
            "the pipeline stage that detected expiry: admission (gate "
            "before any work), queue (expired while batched — dropped "
            "without occupying a device slot), wait (the caller's "
            "remaining budget ran out waiting on the batch result)",
            ["stage"],
            registry=self.registry,
        )
        self.requests_shed_total = prom.Counter(
            "keto_tpu_requests_shed_total",
            "Check admissions rejected with a typed OverloadedError "
            "(429 / RESOURCE_EXHAUSTED, Retry-After attached) before any "
            "work was done, by reason: queue_full (admitted-but-"
            "unresolved checks at serve.check.max_queue), draining (the "
            "daemon's shutdown grace window)",
            ["reason"],
            registry=self.registry,
        )
        self.batcher_queue_limit = prom.Gauge(
            "keto_tpu_batcher_queue_limit",
            "Configured admission bound on admitted-but-unresolved "
            "checks per batching plane (serve.check.max_queue; 0 = "
            "unbounded). Compare with keto_tpu_batcher_queue_depth for "
            "rejection headroom",
            ["plane"],  # threaded | aio
            registry=self.registry,
        )
        self.breaker_state = prom.Gauge(
            "keto_tpu_breaker_state",
            "Device-path circuit breaker state: 0 closed (device "
            "serving), 1 open (every check degraded to the exact host "
            "oracle — correct answers, degraded latency), 2 half-open "
            "(one probe batch deciding recovery)",
            registry=self.registry,
        )
        self.breaker_transitions_total = prom.Counter(
            "keto_tpu_breaker_transitions_total",
            "Circuit-breaker state transitions, labeled by the state "
            "entered (closed | open | half_open) — the closed -> open -> "
            "half-open -> closed recovery cycle is countable from scrapes "
            "alone",
            ["to"],
            registry=self.registry,
        )
        # store-outage degradation plane (storage/health.py): the
        # store-path twin of the device breaker above — when SQL dies,
        # reads degrade onto the HBM mirror at its covered version,
        # writes shed typed 503s, and the whole episode is observable
        self.store_breaker_state = prom.Gauge(
            "keto_tpu_store_breaker_state",
            "Store-path circuit breaker state: 0 closed (store serving), "
            "1 open (reads the mirror covers served degraded at its "
            "covered version, everything else typed 503), 2 half-open "
            "(one probe read deciding recovery)",
            registry=self.registry,
        )
        self.store_breaker_transitions_total = prom.Counter(
            "keto_tpu_store_breaker_transitions_total",
            "Store-path breaker transitions, labeled by the state "
            "entered (closed | open | half_open) — the outage -> "
            "degraded-serve -> probe -> recovery cycle is countable "
            "from scrapes alone",
            ["to"],
            registry=self.registry,
        )
        self.store_op_timeouts_total = prom.Counter(
            "keto_tpu_store_op_timeouts_total",
            "Store ops that exceeded their per-op budget "
            "(store.op_timeout_ms / store.bulk_timeout_ms on the "
            "bounded executor) and answered the caller with a typed "
            "StoreTimeoutError instead of pinning its thread, by op",
            ["op"],
            registry=self.registry,
        )
        self.store_op_failures_total = prom.Counter(
            "keto_tpu_store_op_failures_total",
            "Store ops that failed outright (driver/disk/injected "
            "error; timeouts are counted separately) — consecutive "
            "failures trip the store breaker, by op",
            ["op"],
            registry=self.registry,
        )
        self.store_unavailable_total = prom.Counter(
            "keto_tpu_store_unavailable_total",
            "Store ops rejected fail-fast with a typed 503 because the "
            "store breaker was open (no store contact was made), by op",
            ["op"],
            registry=self.registry,
        )
        self.store_degraded_serves_total = prom.Counter(
            "keto_tpu_store_degraded_serves_total",
            "Requests answered in DEGRADED mode during a store outage, "
            "by surface: snaptoken (enforcement fell back to the "
            "mirror's covered version), check/filter/expand/list (the "
            "engine served from the device mirror + delta overlay at "
            "its covered version — the response snaptoken IS the "
            "staleness bound), watch (in-band DEGRADED markers pushed "
            "to subscribers instead of a silent stall)",
            ["surface"],
            registry=self.registry,
        )
        self.mirror_staleness_age_seconds = prom.Gauge(
            "keto_tpu_mirror_staleness_age_seconds",
            "Seconds since the default network's device mirror last "
            "confirmed it covered the store's current version (0 while "
            "healthy; grows during a store outage — the "
            "serve.check.degraded.max_staleness_s ceiling converts a "
            "silently-ancient mirror into typed 503s)",
            registry=self.registry,
        )
        self.check_batch_failed_total = prom.Counter(
            "keto_tpu_check_batch_failed_total",
            "Engine batch evaluations that failed, by cause: device "
            "(submit/resolve raised; riders re-answered by the host "
            "oracle), device_timeout (launch watchdog abandoned a batch "
            "past serve.check.device_timeout_ms; riders re-answered by "
            "the host oracle), engine (a non-split-phase engine raised; "
            "riders fail with a typed KetoError), host (the host-oracle "
            "fallback itself raised), keto (a typed KetoError propagated "
            "as-is), store (a store outage reached the submit path — "
            "counted here, owned by the STORE breaker, never recorded "
            "as device-health evidence)",
            ["cause"],
            registry=self.registry,
        )
        # engine flight recorder (this module's FlightRecorder + the
        # kernel launch counters, engine/kernel.py STAT_*): the device
        # side of every launch measured instead of projected
        self.launch_iterations = prom.Histogram(
            "keto_tpu_launch_iterations",
            "BFS loop iterations actually executed per device check "
            "launch (the counted-loop budget is keto_tpu_launch_step_cap; "
            "iterations == cap with live tasks means step-exhausted host "
            "replays)",
            registry=self.registry,
            buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48),
        )
        self.launch_step_cap = prom.Gauge(
            "keto_tpu_launch_step_cap",
            "Static step budget (max_steps) of the most recent device "
            "check launch — the denominator for iterations-vs-cap",
            registry=self.registry,
        )
        self.launch_frontier_peak = prom.Histogram(
            "keto_tpu_launch_frontier_peak",
            "Peak per-step frontier task count within one device check "
            "launch (capacity is the launch frontier_cap; peaks at cap "
            "mean frontier-overflow host replays are near)",
            registry=self.registry,
            buckets=(16, 64, 256, 1024, 4096, 16384, 65536),
        )
        self.launch_gather_bytes = prom.Histogram(
            "keto_tpu_launch_gather_bytes",
            "Estimated bytes moved by the kernel's gather sites per "
            "device check launch (engine/kernel.py "
            "estimate_step_gather_bytes x iterations used) — the "
            "measured stand-in for the gather-volume droop hypothesis",
            registry=self.registry,
            buckets=(
                1e5, 1e6, 4e6, 1.6e7, 6.4e7, 2.56e8, 1e9, 4e9,
            ),
        )
        self.launch_edge_rows = prom.Histogram(
            "keto_tpu_launch_edge_rows",
            "Candidate rows materially gathered per device check launch "
            "(valid expansion children across all steps) — the dynamic "
            "half of gather volume, scales with graph fanout",
            registry=self.registry,
            buckets=(1, 10, 100, 1000, 10000, 100000, 1000000),
        )
        self.launch_padding_waste = prom.Histogram(
            "keto_tpu_launch_padding_waste",
            "Padded fraction of the launch bucket ((B - real) / B): 0 = "
            "full bucket, 0.9 = 90% of the launch cost spent on padding "
            "rows",
            registry=self.registry,
            buckets=(0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99),
        )
        self.flightrec_dumps_total = prom.Counter(
            "keto_tpu_flightrec_dumps_total",
            "Flight-recorder auto-dumps, by reason (device | "
            "device_timeout | host | manual): each dump writes the ring "
            "tail to the log before the failure evidence scrolls out",
            ["reason"],
            registry=self.registry,
        )
        self.hbm_table_bytes = prom.Gauge(
            "keto_tpu_hbm_table_bytes",
            "Device bytes held per buffer family of the default "
            "network's mirror (check = packed check tables incl. the "
            "delta overlay, expand / reverse / subjects = the lazy "
            "read-path extras) — refreshed by TPUCheckEngine."
            "hbm_snapshot(), which GET /admin/flightrec calls",
            ["buffer"],
            registry=self.registry,
        )
        self.client_retries_total = prom.Counter(
            "keto_tpu_client_retries_total",
            "In-process ReadClient retries (resilience.RetryPolicy: "
            "exponential backoff + full jitter, UNAVAILABLE/"
            "RESOURCE_EXHAUSTED only, idempotent reads only, deadline-"
            "budget-aware) — fed when a RetryPolicy is constructed with "
            "this counter (embedders, bench, load tools)",
            registry=self.registry,
        )
        # multi-replica serving plane (api/replica.py): N serve workers
        # over one device engine, snaptoken-routed consistency, and
        # deadline-budget-aware request hedging (Zanzibar §2.4.1/§4)
        self.worker_checks_total = prom.Counter(
            "keto_tpu_worker_checks_total",
            "Check() requests answered per replica serve worker (replica "
            "mode only, serve.check.workers >= 2) — the per-worker QPS "
            "breakdown the bench records; routed requests count on the "
            "ANSWERING worker",
            ["worker"],
            registry=self.registry,
        )
        self.replica_applied_version = prom.Gauge(
            "keto_tpu_replica_applied_version",
            "Store version a replica serve worker has applied from its "
            "Watch-changelog tail (default network; compare across "
            "workers for replica lag — snaptoken routing holds/routes "
            "requests demanding newer versions)",
            ["worker"],
            registry=self.registry,
        )
        self.replica_routed_total = prom.Counter(
            "keto_tpu_replica_routed_total",
            "Checks whose snaptoken demanded a version newer than the "
            "receiving worker's applied version, by resolution: "
            "caught_up (the worker's tail applied it within the "
            "catch-up hold), routed (proxied to a fresh worker), "
            "escalated (no worker fresh — served at the live store "
            "version; still never stale)",
            ["outcome"],
            registry=self.registry,
        )
        self.hedge_launched_total = prom.Counter(
            "keto_tpu_hedge_launched_total",
            "Hedge duplicates launched (a check unanswered within the "
            "hedge policy's latency quantile fired one duplicate onto "
            "another worker's batcher; deadline-budget-aware — a budget "
            "too thin to fit a hedge never fires one)",
            registry=self.registry,
        )
        self.hedge_wins_total = prom.Counter(
            "keto_tpu_hedge_wins_total",
            "Hedged checks resolved, by which ride answered first "
            "(primary | hedge) — first answer wins, the loser is "
            "cancelled",
            ["ride"],
            registry=self.registry,
        )
        self.hedge_cancelled_total = prom.Counter(
            "keto_tpu_hedge_cancelled_total",
            "Losing hedge rides cancelled before their batch launched "
            "(a cancelled pending never occupies a device batch slot)",
            registry=self.registry,
        )
        # multi-daemon HA plane (api/follower.py, api/router.py,
        # tools/ha_smoke.py): Watch-fed follower mirrors + snaptoken-safe
        # cross-process failover (Zanzibar §2.4 multi-cluster serving)
        self.ha_applied_version = prom.Gauge(
            "keto_tpu_ha_applied_version",
            "Leader store version this follower daemon has applied from "
            "its network Watch-changelog tail, per network id — the "
            "version its snaptoken gate enforces; compare against the "
            "leader's keto_tpu_store_version-equivalent for fleet lag",
            ["nid"],
            registry=self.registry,
        )
        self.ha_version_lag = prom.Gauge(
            "keto_tpu_ha_version_lag",
            "Versions between the leader tail this follower has OBSERVED "
            "(latest watch frame) and what it has APPLIED, per network "
            "id — sustained nonzero means the apply path is behind, not "
            "the network",
            ["nid"],
            registry=self.registry,
        )
        self.ha_tail_state = prom.Gauge(
            "keto_tpu_ha_tail_state",
            "Follower changelog-tail state (0 disconnected, 1 "
            "bootstrapping, 2 tailing) — the rotation signal the front "
            "router's health probes reflect",
            ["nid"],
            registry=self.registry,
        )
        self.ha_bootstrap_reads_total = prom.Counter(
            "keto_tpu_ha_bootstrap_reads_total",
            "Full leader store sweeps the follower performed (cold start "
            "with no usable checkpoint, or a watch RESET gap). The HA "
            "smoke pins this at its floor to prove steady state is "
            "changelog-fed — zero full reads after cold start",
            registry=self.registry,
        )
        self.ha_stream_reconnects_total = prom.Counter(
            "keto_tpu_ha_stream_reconnects_total",
            "Follower watch-stream reconnects, by cause: silent (no "
            "frame within follower.liveness_s — the severed-connection "
            "detector), error (transport error / stream end), reset "
            "(server RESET forced a re-bootstrap), stale (snaptoken "
            "ahead of the leader — leader lost state, resync)",
            ["cause"],
            registry=self.registry,
        )
        self.ha_failovers_total = prom.Counter(
            "keto_tpu_ha_failovers_total",
            "Requests the HA front router re-routed away from a failed "
            "or lagging daemon mid-call (the kill -9 smoke's failover "
            "counter; latency to the winning answer is the failover "
            "latency the smoke bounds)",
            registry=self.registry,
        )
        self.ha_rotation_state = prom.Gauge(
            "keto_tpu_ha_rotation_state",
            "Router rotation membership per backend daemon (1 in "
            "rotation, 0 drained — breaker open or probes failing); "
            "drained daemons keep being probed and rejoin on recovery",
            ["target"],
            registry=self.registry,
        )
        # crash-recovery plane (engine/scrub.py, engine/checkpoint.py,
        # tools/crash_smoke.py): cold-start recovery + anti-entropy
        self.checkpoint_load_fallbacks_total = prom.Counter(
            "keto_tpu_checkpoint_load_fallbacks_total",
            "Warm-restart mirror checkpoints that existed but could not "
            "be used, by reason: corrupt (torn/truncated/incompatible "
            "file — crash mid-write or format drift) or stale (valid "
            "file for another (store version, config) pair). Either way "
            "the engine rebuilt from the store — the fallback is the "
            "contract, this counts how often it fires",
            ["reason"],
            registry=self.registry,
        )
        self.checkpoint_write_failures_total = prom.Counter(
            "keto_tpu_checkpoint_write_failures_total",
            "Mirror checkpoint writes that failed (full disk, revoked "
            "mount) — deferred-flush OSErrors and shutdown-flush "
            "failures both count; serving and drain continue either "
            "way (the store is the durability, the checkpoint is a "
            "warm-restart optimization)",
            registry=self.registry,
        )
        self.scrub_passes_total = prom.Counter(
            "keto_tpu_scrub_passes_total",
            "Completed anti-entropy scrub passes (every engine's device "
            "mirror fully checksummed against the host truth once per "
            "pass; incremental slices — scrub.slice_rows — spread the "
            "work across the interval)",
            registry=self.registry,
        )
        self.scrub_slices_total = prom.Counter(
            "keto_tpu_scrub_slices_total",
            "Device-mirror table slices checksummed by the anti-entropy "
            "scrubber (engine/scrub.py)",
            registry=self.registry,
        )
        self.scrub_divergence_total = prom.Counter(
            "keto_tpu_scrub_divergence_total",
            "Device-mirror slices whose checksum DIVERGED from the host "
            "recomputation at the mirror's covered version, by device "
            "table — a silent HBM/table corruption caught by the "
            "scrubber; each divergence dumps the flight-recorder tail "
            "and triggers the breaker-degrade auto-repair",
            ["table"],
            registry=self.registry,
        )
        self.scrub_repairs_total = prom.Counter(
            "keto_tpu_scrub_repairs_total",
            "Automatic mirror repairs triggered by scrub divergence: "
            "the breaker opens (checks host-oracle-serve, staying "
            "correct), the poisoned state is dropped, and the next "
            "check rebuilds the mirror from the store",
            registry=self.registry,
        )
        # Leopard closure index (engine/closure.py): deep checks answered
        # by the precomputed transitive-closure sets in one probe step
        self.closure_hits_total = prom.Counter(
            "keto_tpu_closure_hits_total",
            "Check() queries answered by the Leopard closure index "
            "(covered node, clean overlay, index synced through the "
            "serving state's version) — positives AND definitive "
            "negatives both count; every hit skipped the per-level BFS "
            "entirely",
            registry=self.registry,
        )
        self.closure_fallback_total = prom.Counter(
            "keto_tpu_closure_fallback_total",
            "Check() queries the closure index declined, by cause: "
            "kernel-side `uncovered` (poisoned/oversized/unindexed "
            "node), `dirty` (write-perturbed since the last powering), "
            "`unindexed` (query vocabulary never encoded) and host-side "
            "`unbuilt`/`stale_snapshot`/`lag` (index not ready for the "
            "serving state — the batch never launched a closure probe). "
            "Fallbacks ride the BFS kernel: correct, depth-priced",
            ["cause"],
            registry=self.registry,
        )
        self.closure_lag_versions = prom.Gauge(
            "keto_tpu_closure_lag_versions",
            "Store versions the closure index's dirty overlay trails the "
            "serving state by (0 = synced; answers are version-gated, so "
            "lag costs latency, never correctness)",
            registry=self.registry,
        )
        self.closure_builds_total = prom.Counter(
            "keto_tpu_closure_builds_total",
            "Closure index powerings (initial build + re-powerings after "
            "dirty-overlay overflow / changelog resets / snapshot "
            "rebuilds)",
            registry=self.registry,
        )
        self.closure_entries = prom.Gauge(
            "keto_tpu_closure_entries",
            "Materialized (node, subject) closure entries in the current "
            "index build (the R·D product's row count on device)",
            registry=self.registry,
        )
        # on-device GraphBLAS powering (engine/closure_power.py): the
        # closure built AS bit-packed boolean matmul on the accelerator
        # when closure.powering = "device" (host stays the fallback)
        self.closure_power_builds_total = prom.Counter(
            "keto_tpu_closure_power_builds_total",
            "Closure powerings completed BY the device GraphBLAS kernel "
            "(closure.powering = device; host-fallback powerings count "
            "under keto_tpu_closure_builds_total only)",
            registry=self.registry,
        )
        self.closure_power_steps_total = prom.Counter(
            "keto_tpu_closure_power_steps_total",
            "frontier×adjacency powering steps executed on device across "
            "all waves (each step is one bit-packed boolean matmul level "
            "under the shared bounded loop)",
            registry=self.registry,
        )
        self.closure_power_bytes = prom.Gauge(
            "keto_tpu_closure_power_bytes",
            "Device working-set bytes of the most recent device powering "
            "(packed adjacency operands + seen/frontier bit matrices + "
            "unpacked step scratch; transient — freed after the build)",
            registry=self.registry,
        )
        # bulk ACL filtering (engine/filter_kernel.py): one subject,
        # thousands of candidate objects, one device ride
        self.filter_requests_total = prom.Counter(
            "keto_tpu_filter_requests_total",
            "BatchFilter evaluations (engine.filter_batch calls — one "
            "per API request regardless of how many chunks the "
            "candidate list split into)",
            registry=self.registry,
        )
        self.filter_request_objects = prom.Histogram(
            "keto_tpu_filter_request_objects",
            "Candidate-list size per BatchFilter request (the workload's "
            "defining dimension: per-object cost amortizes over it)",
            buckets=(16, 64, 256, 1024, 4096, 10000, 16384, 65536),
            registry=self.registry,
        )
        self.filter_objects_total = prom.Counter(
            "keto_tpu_filter_objects_total",
            "Candidate objects answered, by resolution path: `closure` "
            "(one batched Leopard membership gather — no BFS at all), "
            "`frontier` (the shared-frontier reverse walk intersected "
            "the whole leftover column in one launch), `vocab` (name "
            "unknown to graph+config under a monotone-only config — "
            "definitively invisible, zero work), `host` (cause-coded "
            "exact oracle replay: AND/NOT islands, dirty rows, "
            "overflow, unknown vocabulary under non-monotone configs)",
            ["path"],
            registry=self.registry,
        )
        self.filter_shed_total = prom.Counter(
            "keto_tpu_filter_shed_total",
            "Filter requests rejected before any device work, by "
            "reason: `max_objects` (candidate list over "
            "filter.max_objects — typed 400 so oversized requests "
            "cannot buy unbounded device work)",
            ["reason"],
            registry=self.registry,
        )
        # decision explain plane + OTLP span export (this module's
        # SpanExporter + engine/explain.py): the observability plane's
        # own health counters
        self.explain_requests_total = prom.Counter(
            "keto_tpu_explain_requests_total",
            "Check requests served with explain=true (the DecisionTrace "
            "slow path: cache bypassed, host witness re-walk beside the "
            "authoritative device verdict) — admission-bounded by the "
            "explain.max_per_s token bucket, so this counts served "
            "explains, not shed ones (those land in "
            "keto_tpu_requests_shed_total{explain_rate})",
            registry=self.registry,
        )
        self.otlp_exported_total = prom.Counter(
            "keto_tpu_otlp_exported_total",
            "Spans successfully POSTed to observability.otlp.endpoint "
            "as OTLP/HTTP-JSON by the background SpanExporter",
            registry=self.registry,
        )
        self.otlp_dropped_total = prom.Counter(
            "keto_tpu_otlp_dropped_total",
            "Spans dropped by the OTLP exporter instead of blocking a "
            "request thread, by reason: queue_full (the bounded export "
            "queue was at capacity at enqueue) or post_error (the "
            "collector POST failed/timed out and the batch was "
            "abandoned) — export is observability; dropping beats "
            "back-pressure",
            ["reason"],
            registry=self.registry,
        )
        # workload observatory + SLO plane (observability_workload.py,
        # §5o): per-namespace accounting, hot-key sketch shares, and
        # multi-window burn rates against the BASELINE.json objectives
        self.workload_requests_total = prom.Counter(
            "keto_tpu_workload_requests_total",
            "Answered checks by (namespace, relation, answering tier, "
            "verdict) — the per-workload accounting plane "
            "(observability_workload.py): tier is cache | closure | "
            "device | host | vocab | other (the §5m explain tiers, now "
            "stamped on every check), verdict is allowed | denied. "
            "Label cardinality is bounded by the configured vocabulary "
            "(namespaces x relations), never by request content",
            ["namespace", "relation", "tier", "verdict"],
            registry=self.registry,
        )
        self.workload_tier_duration = prom.Histogram(
            "keto_tpu_workload_tier_duration_seconds",
            "Served request duration by ANSWERING tier (cache | "
            "closure | device | host | vocab | other) — the workload "
            "observatory's per-tier latency attribution: which tier "
            "burns the latency budget, per scrape. OpenMetrics "
            "exposition carries a trace_id exemplar per bucket, same "
            "as the stage histogram",
            ["tier"],
            registry=self.registry,
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 1.0,
            ),
        )
        self.hotkey_share = prom.Gauge(
            "keto_tpu_hotkey_share",
            "Fraction of the sliding hot-key window's traffic answered "
            "by the top-k keys of the Space-Saving sketch "
            "(observability_workload.py): kind is object | subject, k "
            "is 1 | 10 | 100 — the Zanzibar §4 hot-spot instrument as "
            "a scrapeable gauge (join it with "
            "keto_tpu_check_cache_ops_total for cache-hit "
            "attribution); refreshed at most once per second from the "
            "serve path, full detail at GET /admin/hotkeys",
            ["kind", "k"],
            registry=self.registry,
        )
        self.slo_objective_target = prom.Gauge(
            "keto_tpu_slo_objective_target",
            "The configured target per SLO objective "
            "(slo.objectives.*): served_p95_ms in milliseconds, "
            "availability as a fraction, max_staleness_s in seconds — "
            "exported so dashboards and the perf gate judge by the "
            "same number the live burn tracker uses",
            ["objective"],
            registry=self.registry,
        )
        self.slo_burn_rate = prom.Gauge(
            "keto_tpu_slo_burn_rate",
            "Error-budget burn rate per objective and window (short | "
            "long, slo.window_short_s / slo.window_long_s): (bad "
            "fraction over the window) / budget — 1.0 spends the "
            "budget exactly on schedule, above slo.fast_burn_threshold "
            "on BOTH windows is a fast burn (multi-window rule: the "
            "short window catches the spike, the long window keeps one "
            "blip from paging)",
            ["objective", "window"],
            registry=self.registry,
        )
        self.slo_fast_burn_active = prom.Gauge(
            "keto_tpu_slo_fast_burn_active",
            "1 while the objective is in fast burn (burn rate over "
            "slo.fast_burn_threshold on both windows), else 0; every "
            "evaluation tick spent fast-burning also emits a WARNING "
            "log line — never sampled away",
            ["objective"],
            registry=self.registry,
        )
        self.slo_fast_burn_total = prom.Counter(
            "keto_tpu_slo_fast_burn_total",
            "Fast-burn EPISODES per objective (transitions into the "
            "fast-burn state, not ticks spent in it) — the incident "
            "counter an alert acknowledges",
            ["objective"],
            registry=self.registry,
        )
        # hot-path cache: (transport, method) -> (duration child,
        # {code: counter child})
        self._observe_cache: dict = {}
        # stage -> histogram child (stage names are the CHECK_STAGES
        # constants, so this cache is bounded by construction)
        self._stage_cache: dict = {}
        # tier -> histogram child (tier names are the TIERS constants
        # of observability_workload.py — bounded by construction)
        self._tier_cache: dict = {}

    OPENMETRICS_CONTENT_TYPE = (
        "application/openmetrics-text; version=1.0.0; charset=utf-8"
    )

    def export(self) -> bytes:
        return prom.generate_latest(self.registry)

    def export_openmetrics(self) -> bytes:
        """OpenMetrics exposition — the format that carries EXEMPLARS
        (the trace_id attached to check-stage histogram buckets, linking
        the metrics plane to the trace plane); served by the metrics
        listener when the scraper's Accept header asks for it."""
        from prometheus_client.openmetrics import exposition as om

        return om.generate_latest(self.registry)

    def observe_launch(
        self,
        steps: int,
        step_cap: int,
        frontier_max: int,
        gather_bytes: float,
        edge_rows: int,
        padding_waste: float,
    ) -> None:
        """One check launch's counter samples (called once per device
        batch at its resolve sync point)."""
        self.launch_iterations.observe(steps)
        self.launch_step_cap.set(step_cap)
        self.launch_frontier_peak.observe(frontier_max)
        self.launch_gather_bytes.observe(gather_bytes)
        self.launch_edge_rows.observe(edge_rows)
        self.launch_padding_waste.observe(padding_waste)

    def observe_stage(
        self, stage: str, seconds: float, trace_id: Optional[str] = None
    ) -> None:
        """One per-stage sample (cached label child; see observe_request
        for why `.labels()` is avoided on the serve hot path).

        `trace_id` attaches an OpenMetrics EXEMPLAR to the bucket this
        observation lands in: a scrape of the stage histogram then
        carries a concrete trace id per bucket — the metrics->trace join
        Grafana/Tempo navigate on. Costs one small dict per exemplared
        observation; callers pass it only when a request context exists."""
        child = self._stage_cache.get(stage)
        if child is None:
            child = self._stage_cache[stage] = (
                self.check_stage_duration.labels(stage)
            )
        if trace_id:
            child.observe(seconds, exemplar={"trace_id": trace_id})
        else:
            child.observe(seconds)

    def observe_tier(
        self, tier: str, seconds: float, trace_id: Optional[str] = None
    ) -> None:
        """One served request's duration attributed to its ANSWERING
        tier (cached label child, exemplared like observe_stage — the
        workload observatory's per-tier latency feed)."""
        child = self._tier_cache.get(tier)
        if child is None:
            child = self._tier_cache[tier] = (
                self.workload_tier_duration.labels(tier)
            )
        if trace_id:
            child.observe(seconds, exemplar={"trace_id": trace_id})
        else:
            child.observe(seconds)

    def observe_request(self, transport: str, method: str):
        """Times a request and counts its outcome code.

        Label-child resolution (`.labels(...)`) walks locked dicts in
        prometheus_client; on the serve hot path (thousands of calls/sec
        on a 1-core host) that shows up, so children are cached per
        (transport, method[, code]). Label sets stay route-constant by
        construction — the cache cannot grow unboundedly."""
        key = (transport, method)
        cached = self._observe_cache.get(key)
        if cached is None:
            cached = (
                self.request_duration.labels(transport, method),
                {"OK": self.requests_total.labels(transport, method, "OK")},
            )
            self._observe_cache[key] = cached
        return _RequestObservation(self, key, cached)


class _RequestObservation:
    """Plain-class context manager for observe_request (a generator CM
    costs ~2x more per request; this path runs per RPC)."""

    __slots__ = ("_metrics", "_key", "_cached", "_start", "code")

    def __init__(self, metrics, key, cached):
        self._metrics = metrics
        self._key = key
        self._cached = cached
        self.code = "OK"

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        duration_child, counters = self._cached
        duration_child.observe(time.perf_counter() - self._start)
        counter = counters.get(self.code)
        if counter is None:
            counter = self._metrics.requests_total.labels(*self._key, self.code)
            counters[self.code] = counter
        counter.inc()
        return False

    # dict-style writes kept for handler compatibility
    # (handlers do `outcome["code"] = ...`)
    def __setitem__(self, k, v):
        if k == "code":
            self.code = v

    def __getitem__(self, k):
        if k == "code":
            return self.code
        raise KeyError(k)


class _NoopSpan:
    def set_attribute(self, *a, **k):
        pass

    def record_exception(self, *a, **k):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NOOP_SPAN = _NoopSpan()


class _NoopTracer:
    # False lets hot paths skip per-request span bookkeeping entirely
    active = False

    def span(self, name: str, ctx=None, root: bool = False, **attrs):
        # singleton CM: no generator frame per call on the serve path
        return _NOOP_SPAN

    def record(self, name: str, ctx=None, duration_s=None, **attrs):
        pass


class RecordedSpan:
    __slots__ = ("name", "attrs")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs

    def set_attribute(self, key, value):
        self.attrs[key] = value

    def record_exception(self, err):
        self.attrs["exception"] = repr(err)


class RecordingTracer:
    """In-memory span recorder (`tracing.provider: memory`): the test/
    debug exporter — this image ships only the OTel API, not the SDK, so
    span visibility needs a built-in sink. Thread-safe append-only.

    Spans carry trace correlation: an explicit `ctx` (SpanContext) or,
    when absent, the executing request's CURRENT_TRACE — so persistence
    spans recorded deep in a handler share the request's trace_id
    without any signature changes.

    `root=True` marks the request's TRANSPORT span: it takes the
    context's OWN span id (instead of minting a child) so every other
    span in the request — batcher.queue, engine stages, store ops —
    parent-links to it, and its own parent is the caller's span id from
    the ingested `traceparent` (ctx.parent_span_id). That's what turns
    the flat recording into a real parent-linked trace at an OTel
    collector.

    `exporter` (SpanExporter | None) receives every COMPLETED span that
    carries a trace id — the OTLP/HTTP-JSON export plane. Enqueue is
    non-blocking by contract (bounded queue, drop counter)."""

    active = True

    def __init__(self, cap: int = 4096, exporter=None):
        import collections

        self.spans = collections.deque(maxlen=cap)
        self.exporter = exporter

    @staticmethod
    def _trace_attrs(ctx, attrs: dict, root: bool = False) -> dict:
        if ctx is None:
            rt = CURRENT_TRACE.get()
            ctx = rt.ctx if rt is not None else None
        if ctx is not None:
            attrs["trace_id"] = ctx.trace_id
            if root:
                # the transport span IS the request's span: ctx.span_id
                # is what every nested span parents to, and the caller's
                # client span (parent_span_id) is what THIS span parents
                # to across the process boundary
                attrs["span_id"] = ctx.span_id
                if ctx.parent_span_id:
                    attrs["parent_span_id"] = ctx.parent_span_id
            else:
                attrs["parent_span_id"] = ctx.span_id
                attrs["span_id"] = secrets.token_hex(8)
        return attrs

    def _export(self, s: "RecordedSpan") -> None:
        if self.exporter is not None and "trace_id" in s.attrs:
            self.exporter.enqueue(s)

    @contextlib.contextmanager
    def span(self, name: str, ctx=None, root: bool = False, **attrs):
        s = RecordedSpan(name, self._trace_attrs(ctx, dict(attrs), root))
        self.spans.append(s)
        start = time.perf_counter()
        try:
            yield s
        finally:
            s.attrs["duration_ms"] = round(
                (time.perf_counter() - start) * 1e3, 3
            )
            # monotonic END stamp: the exporter anchors it to the epoch
            # (wall clocks are banned repo-wide; one anchored conversion
            # at the export boundary is the OTLP wire requirement)
            s.attrs.setdefault("t_mono", time.monotonic())
            self._export(s)

    def record(self, name: str, ctx=None, duration_s=None, **attrs):
        """Retroactive span: stages measured after the fact (batcher
        queue wait, batch-shared engine stages) become spans without a
        live context manager around the work."""
        attrs = self._trace_attrs(ctx, dict(attrs))
        if duration_s is not None:
            attrs["duration_ms"] = round(duration_s * 1e3, 3)
        attrs.setdefault("t_mono", time.monotonic())
        s = RecordedSpan(name, attrs)
        self.spans.append(s)
        self._export(s)

    def span_names(self) -> list:
        return [s.name for s in self.spans]

    def spans_for_trace(self, trace_id: str) -> list:
        return [s for s in self.spans if s.attrs.get("trace_id") == trace_id]


class TracedManager:
    """Span-per-store-op proxy around any Manager implementation — the
    analog of the reference's otel spans in every persister method
    (internal/persistence/sql/relationtuples.go:203-205 etc.) without
    touching the store classes.

    Every public Manager method is either in _TRACED or in _EXEMPT (with
    the reason); tests/test_observability.py asserts the union covers
    the real store classes, so a new store op cannot silently bypass the
    span proxy again (the PR-2 watch ops did)."""

    _TRACED = (
        "get_relation_tuples", "write_relation_tuples",
        "delete_relation_tuples", "delete_all_relation_tuples",
        "transact_relation_tuples", "relation_tuple_exists",
        "all_relation_tuples",
        # watch-era store ops (PR 2): the changelog reads feeding the
        # delta overlay and the watch hub's versioned tail
        "changes_since", "changelog_since",
        # scale/ingest ops: O(edges) reads/writes are exactly the spans
        # an operator wants to see
        "all_tuple_columns", "bulk_load",
        # migration runners (operator-invoked, slow, worth a span)
        "migrate_up", "migrate_down",
        "map_strings_to_uuids", "map_uuids_to_strings",
    )
    # public methods deliberately NOT traced, with the reason — the
    # coverage test fails on any public store method in neither tuple
    _EXEMPT = (
        "version",             # per-batch staleness counter read (hot path)
        "add_write_listener",  # one-time hook registration, not an op
        "set_trim_guard",      # registration; guard runs inside store locks
        "migration_status",    # trivial metadata read (CLI status verb)
        "legacy_row_count",    # trivial metadata read (migration gate)
        "close",               # teardown; tracer may already be gone
    )

    def __init__(self, inner, tracer):
        self._inner = inner
        self._tracer = tracer

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in self._TRACED and callable(attr):
            tracer = self._tracer

            def traced(*args, **kwargs):
                with tracer.span(f"persistence.{name}"):
                    return attr(*args, **kwargs)

            return traced
        return attr


class _OtelTracer:
    active = True

    def __init__(self, service_name: str):
        from opentelemetry import trace

        self._tracer = trace.get_tracer(service_name)

    @contextlib.contextmanager
    def span(self, name: str, ctx=None, root: bool = False, **attrs):
        with self._tracer.start_as_current_span(name) as s:
            if ctx is not None:
                s.set_attribute("keto.trace_id", ctx.trace_id)
            for k, v in attrs.items():
                s.set_attribute(k, v)
            yield s

    def record(self, name: str, ctx=None, duration_s=None, **attrs):
        # the OTel API (no SDK) has no retroactive-span surface; emit a
        # zero-length span carrying the duration as an attribute
        if duration_s is not None:
            attrs["duration_ms"] = round(duration_s * 1e3, 3)
        with self.span(name, ctx=ctx, **attrs):
            pass


# -- OTLP/HTTP-JSON span export ------------------------------------------------


def _otlp_value(v) -> dict:
    """One attribute value in OTLP AnyValue JSON shape."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # OTLP JSON carries int64 as string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


# span-record attrs that are structural (identity/timing), not payload —
# everything else exports as OTLP span attributes
_SPAN_STRUCTURAL = frozenset(
    ("trace_id", "span_id", "parent_span_id", "duration_ms", "t_mono",
     "launch_id", "launch_ids")
)


class SpanExporter:
    """Background OTLP/HTTP-JSON span exporter — stdlib wire format, no
    OTel SDK. The missing half of the PR-3 telemetry plane: the spans
    the RecordingTracer already correlates per trace_id leave the
    process as real OTLP `resourceSpans`, so the trace_id a client sent
    as `traceparent` comes back out as a parent-linked multi-span trace
    in any OTel collector/Jaeger.

    Contract with the serve hot path:
      - `enqueue` NEVER blocks: a bounded queue.Queue absorbs bursts,
        overflow increments `keto_tpu_otlp_dropped_total{queue_full}`
        and the span is gone — export is observability, dropping beats
        back-pressuring a request thread.
      - one daemon worker thread drains the queue in batches (at most
        `batch_max` spans per POST, at least every `flush_interval_s`)
        and POSTs to `observability.otlp.endpoint` with a bounded
        timeout; a failed POST counts its batch as
        dropped{post_error} and moves on — a dead collector costs
        drops, never latency.
      - timestamps: spans carry MONOTONIC end stamps (wall clocks are
        banned repo-wide, ketolint clock-monotonic); ONE epoch anchor
        captured at construction converts them to the unixNano the OTLP
        wire requires (time.time_ns is the sanctioned single wall-clock
        read — it is never used for interval math).
      - flight-recorder correlation: a span's `launch_id`/`launch_ids`
        attr becomes OTLP span EVENTS (name `flightrec.launch`), so a
        trace in Jaeger points straight at its GET /admin/flightrec
        ring entries.

    `flush(timeout)` blocks until everything enqueued so far has been
    POSTed (tests, daemon drain); `close()` stops the worker after a
    final flush attempt."""

    def __init__(
        self,
        endpoint: str,
        metrics=None,
        queue_size: int = 2048,
        flush_interval_s: float = 0.2,
        batch_max: int = 512,
        post_timeout_s: float = 2.0,
        service_name: str = "keto_tpu",
        instance_id: str = "",
    ):
        import os
        import queue as _queue

        self.endpoint = endpoint
        self.metrics = metrics
        self.flush_interval_s = max(float(flush_interval_s), 0.01)
        self.batch_max = max(int(batch_max), 1)
        self.post_timeout_s = float(post_timeout_s)
        self.service_name = service_name
        self.instance_id = instance_id or str(os.getpid())
        self._q: "_queue.Queue" = _queue.Queue(maxsize=max(int(queue_size), 1))
        self._stop = threading.Event()
        # flush accounting: enqueued vs settled (exported OR dropped);
        # flush() waits for settled to catch up under one condition
        self._mu = threading.Lock()
        self._settle_cond = threading.Condition(self._mu)
        self._enqueued = 0
        self._settled = 0
        self.stats = {"exported": 0, "dropped_queue_full": 0,
                      "dropped_post_error": 0, "posts": 0}
        # the ONE wall-clock read: an epoch anchor for OTLP unixNano
        # stamps; every span time is anchor + (its monotonic - anchor's)
        self._anchor_epoch_ns = time.time_ns()
        self._anchor_mono = time.monotonic()
        self._thread = threading.Thread(
            target=self._loop, name="keto-otlp-export", daemon=True
        )
        self._thread.start()

    # -- hot-path surface ------------------------------------------------------

    def enqueue(self, span) -> bool:
        """Queue one completed RecordedSpan for export. Non-blocking:
        False (+ drop counter) when the bounded queue is full."""
        import queue as _queue

        if self._stop.is_set():
            return False
        with self._mu:
            self._enqueued += 1
        try:
            self._q.put_nowait(span)
            return True
        except _queue.Full:
            self._drop(1, "queue_full")
            return False

    # -- bookkeeping -----------------------------------------------------------

    def _settle(self, n: int) -> None:
        with self._settle_cond:
            self._settled += n
            self._settle_cond.notify_all()

    def _drop(self, n: int, reason: str) -> None:
        self.stats[f"dropped_{reason}"] += n
        if self.metrics is not None:
            self.metrics.otlp_dropped_total.labels(reason).inc(n)
        self._settle(n)

    def _mark_exported(self, n: int) -> None:
        self.stats["exported"] += n
        if self.metrics is not None:
            self.metrics.otlp_exported_total.inc(n)
        self._settle(n)

    # -- worker ----------------------------------------------------------------

    def _loop(self) -> None:
        import queue as _queue

        # TICK-based, not wake-per-span: a blocking q.get would wake
        # this worker (json.dumps + POST, GIL-holding) the instant a
        # request thread enqueues — measured 1.2x serve latency on a
        # 2-core box. Sleeping the flush interval and draining in
        # batches decouples export work from request threads entirely;
        # the cost is at most one interval of added export delay.
        while True:
            stopped = self._stop.wait(self.flush_interval_s)
            while True:
                batch = []
                while len(batch) < self.batch_max:
                    try:
                        batch.append(self._q.get_nowait())
                    except _queue.Empty:
                        break
                if not batch:
                    break
                self._post(batch)
            if stopped:
                return

    def _epoch_ns(self, mono: float) -> int:
        return self._anchor_epoch_ns + int(
            (mono - self._anchor_mono) * 1e9
        )

    def _otlp_span(self, s) -> dict:
        attrs = s.attrs
        end_mono = attrs.get("t_mono", self._anchor_mono)
        end_ns = self._epoch_ns(end_mono)
        dur_ms = float(attrs.get("duration_ms", 0.0) or 0.0)
        start_ns = end_ns - int(dur_ms * 1e6)
        out = {
            "traceId": attrs.get("trace_id", ""),
            "spanId": attrs.get("span_id", ""),
            "name": s.name,
            "kind": 2,  # SPAN_KIND_SERVER-side work
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(end_ns),
            "attributes": [
                {"key": k, "value": _otlp_value(v)}
                for k, v in attrs.items()
                if k not in _SPAN_STRUCTURAL
            ],
        }
        parent = attrs.get("parent_span_id")
        if parent:
            out["parentSpanId"] = parent
        # flight-recorder launch ids ride as span EVENTS: the join key
        # into GET /admin/flightrec, visible per span in the collector
        launch_ids = tuple(
            lid for lid in (attrs.get("launch_ids") or ()) if lid is not None
        )
        if attrs.get("launch_id") is not None:
            launch_ids = (*launch_ids, attrs["launch_id"])
        if launch_ids:
            out["events"] = [
                {
                    "timeUnixNano": str(end_ns),
                    "name": "flightrec.launch",
                    "attributes": [
                        {"key": "launch_id", "value": _otlp_value(int(lid))}
                    ],
                }
                for lid in launch_ids
            ]
        return out

    def payload(self, spans: list) -> bytes:
        """The OTLP/HTTP-JSON request body for one span batch (public:
        the smoke validates the wire shape without a collector)."""
        import json as _json

        return _json.dumps({
            "resourceSpans": [{
                "resource": {
                    "attributes": [
                        {"key": "service.name",
                         "value": {"stringValue": self.service_name}},
                        {"key": "service.instance.id",
                         "value": {"stringValue": self.instance_id}},
                    ]
                },
                "scopeSpans": [{
                    "scope": {"name": "keto_tpu"},
                    "spans": [self._otlp_span(s) for s in spans],
                }],
            }]
        }).encode()

    def _post(self, batch: list) -> None:
        import urllib.request

        try:
            body = self.payload(batch)
            req = urllib.request.Request(
                self.endpoint, data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=self.post_timeout_s):
                pass
            self.stats["posts"] += 1
            self._mark_exported(len(batch))
        except Exception as e:  # noqa: BLE001 — a dead collector must
            # never fail (or slow) anything but this counter
            logger.debug("otlp export POST failed: %s", e)
            self._drop(len(batch), "post_error")

    # -- lifecycle -------------------------------------------------------------

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every span enqueued BEFORE this call is settled
        (exported or dropped); False on timeout."""
        deadline = time.monotonic() + timeout
        with self._settle_cond:
            target = self._enqueued
            while self._settled < target:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._settle_cond.wait(remaining)
        return True

    def close(self, timeout: float = 2.0) -> None:
        self.flush(timeout)
        self._stop.set()
        # wake the worker out of its queue.get wait
        self._thread.join(timeout=max(self.flush_interval_s * 2, 0.5))


def build_tracer(config, exporter=None):
    """ref: otelx tracer built once from config (registry_default.go:118-129).
    `tracing.provider: memory` selects the in-process recording sink.
    A SpanExporter (built by the registry when
    `observability.otlp.endpoint` is set) forces the recording sink —
    the export plane reads our RecordedSpan objects — regardless of
    provider: setting the endpoint IS the opt-in."""
    if exporter is not None:
        return RecordingTracer(exporter=exporter)
    if config.get("tracing.enabled", False):
        if config.get("tracing.provider", "otel") == "memory":
            return RecordingTracer()
        try:
            return _OtelTracer(config.get("tracing.service_name", "keto_tpu"))
        except Exception as e:  # otel mis-setup must never block serving
            logger.warning("tracing disabled: %s", e)
    return _NoopTracer()


def _stages_ms(stages: Optional[dict]) -> dict[str, float]:
    return {k: round(v * 1e3, 3) for k, v in (stages or {}).items()}


class _JsonLogFormatter(logging.Formatter):
    """One JSON object per line (`log.format: json`), carrying the
    structured extras request_log/slow_query_log attach — machine-
    ingestable parity with the reference's logrusx JSON mode."""

    _STD = frozenset(
        logging.LogRecord("", 0, "", 0, "", (), None).__dict__
    ) | {"message", "asctime", "taskName"}

    def format(self, record: logging.LogRecord) -> str:
        import json as _json

        out = {
            "time": self.formatTime(record),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        for k, v in record.__dict__.items():
            if k not in self._STD:
                out[k] = v
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return _json.dumps(out, default=str)


def configure_logging(config) -> None:
    """Apply `log.level` / `log.format` from the config to the keto_tpu
    logger tree (ref: logrusx setup in driver registry). Called by
    Daemon.start so an operator's config controls serve logging without
    code; idempotent — repeated starts just re-apply."""
    level = config.get("log.level")
    if level:
        logger.setLevel(getattr(logging, str(level).upper(), logging.INFO))
    fmt = config.get("log.format")
    json_handlers = [
        h for h in logger.handlers if getattr(h, "_keto_json", False)
    ]
    if fmt == "json":
        if not json_handlers:
            handler = logging.StreamHandler()
            handler._keto_json = True
            handler.setFormatter(_JsonLogFormatter())
            logger.addHandler(handler)
            # the JSON handler replaces root propagation (double lines
            # otherwise: one structured, one from the root handler)
            logger.propagate = False
    elif json_handlers:
        # symmetric: a later start with log.format text (or unset) must
        # UNDO json mode — a stuck handler + propagate=False would hide
        # keto_tpu records from root/caplog for the process's lifetime
        for h in json_handlers:
            logger.removeHandler(h)
        logger.propagate = True


def request_log(
    transport: str,
    method: str,
    code: str,
    duration_s: float,
    trace_id: str = "",
    stages: Optional[dict] = None,
    launch_ids: Optional[list] = None,
    tier: Optional[str] = None,
) -> None:
    """Structured per-request log line (ref: reqlog middleware
    daemon.go:294), now carrying the trace id, the per-stage ms
    breakdown, the flight-recorder launch ids the request rode, and the
    answering tier (cache | closure | device | host | vocab) — the tier
    used to be visible only via explain=true, which bypasses the cache
    and rate-limits. The isEnabledFor gate inside logger.info keeps
    this free on the serve hot path at the default WARNING level."""
    if not logger.isEnabledFor(logging.INFO):
        return
    extra = {
        "transport": transport,
        "method": method,
        "code": code,
        "duration_ms": round(duration_s * 1e3, 3),
    }
    if trace_id:
        extra["trace_id"] = trace_id
    if tier:
        extra["tier"] = tier
    if stages:
        extra["stages_ms"] = _stages_ms(stages)
    if launch_ids:
        extra["launch_ids"] = list(launch_ids)
    logger.info("request handled", extra=extra)


def slow_query_log(
    threshold_ms,
    transport: str,
    method: str,
    code: str,
    duration_s: float,
    trace_id: str = "",
    stages: Optional[dict] = None,
    launch_ids: Optional[list] = None,
    tier: Optional[str] = None,
) -> None:
    """Threshold-configurable slow-query line (`log.slow_query_ms`):
    one structured WARNING with the trace id, the answering tier,
    per-stage ms, and the launch ids of the device batches the request
    rode (join key into `GET /admin/flightrec`), so a single slow
    request is attributable — down to its exact launch record — without
    turning on full request logging. None threshold = disabled; fires
    at duration >= threshold."""
    if threshold_ms is None:
        return
    duration_ms = duration_s * 1e3
    if duration_ms < float(threshold_ms):
        return
    logger.warning(
        "slow request trace_id=%s transport=%s method=%r code=%s "
        "duration_ms=%.3f tier=%s launch_ids=%s stages_ms=%s",
        trace_id or "-",
        transport,
        method,
        code,
        duration_ms,
        tier or "-",
        list(launch_ids or ()),
        _stages_ms(stages),
    )


def finish_request_telemetry(
    metrics,
    threshold_ms,
    transport: str,
    method: str,
    rt: RequestTrace,
    code: str,
    duration_s: float,
    skip_slow: bool = False,
    sample_rate=None,
    workload=None,
) -> None:
    """Shared end-of-request bookkeeping for every transport (REST
    _route, sync-gRPC _observed, aio _observed): computes the transport
    residual stage, feeds the stage histogram ONLY for requests that
    rode the check pipeline (scrapes/lists/writes have no breakdown and
    would pollute the Check attribution), then emits the request and
    slow-query logs. `skip_slow` exempts by-design-long requests (SSE
    watch streams).

    `sample_rate` (log.request_sample_rate, default 1.0) probabilistically
    samples the per-request INFO `request handled` line: at 1M checks/s
    the unconditional line is itself an overload source, so operators
    can dial it down without losing the slow-query WARNINGs — those
    ALWAYS emit (a sampled-out slow request would be exactly the
    evidence an incident needs).

    `workload` (the registry's WorkloadObservatory, or None) receives
    every finished request: per-tier latency histogram, read/write
    accounting, and the SLO engine's latency + availability events —
    the same `skip_slow` flag exempts watch streams from the latency
    objective (still counted for availability)."""
    rode_pipeline = bool(rt.stages)
    rt.add_stage(
        "transport", max(0.0, duration_s - sum(rt.stages.values()))
    )
    if rode_pipeline and metrics is not None:
        metrics.observe_stage(
            "transport", rt.stages["transport"], trace_id=rt.ctx.trace_id
        )
    launch_ids = getattr(rt, "launch_ids", None)
    tier = getattr(rt, "tier", None)
    if workload is not None:
        workload.observe_request(
            method, code, duration_s, tier=tier,
            trace_id=rt.ctx.trace_id, latency_eligible=not skip_slow,
        )
    sampled_in = True
    if sample_rate is not None and float(sample_rate) < 1.0:
        import random as _random

        sampled_in = _random.random() < float(sample_rate)
    if sampled_in:
        request_log(
            transport, method, code, duration_s,
            trace_id=rt.ctx.trace_id, stages=rt.stages,
            launch_ids=launch_ids, tier=tier,
        )
    if not skip_slow:
        slow_query_log(
            threshold_ms, transport, method, code, duration_s,
            trace_id=rt.ctx.trace_id, stages=rt.stages,
            launch_ids=launch_ids, tier=tier,
        )
