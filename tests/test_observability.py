"""Observability plane: config schema validation, tracing spans, W3C
traceparent propagation parity across REST/gRPC/aio, per-stage Check
metrics, request + slow-query logs, the traced-manager coverage
contract, and the on-demand profiler endpoint."""

import json
import logging
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from keto_tpu.config import Config, ConfigError
from keto_tpu.api import ReadClient, open_channel
from keto_tpu.api.daemon import Daemon
from keto_tpu.ketoapi import RelationTuple
from keto_tpu.namespace import Namespace
from keto_tpu.registry import Registry


class TestConfigSchema:
    def test_unknown_top_level_key_rejected(self):
        with pytest.raises(ConfigError) as e:
            Config({"dns": "memory"})  # typo of dsn
        assert "dns" in str(e.value)

    def test_bad_nested_value_names_the_key(self):
        with pytest.raises(ConfigError) as e:
            Config({"limit": {"max_read_depth": "five"}})
        assert "limit.max_read_depth" in str(e.value)

    def test_bad_engine_enum(self):
        with pytest.raises(ConfigError):
            Config({"check": {"engine": "gpu"}})

    def test_set_validates_and_rolls_back(self):
        cfg = Config({"limit": {"max_read_depth": 5}})
        with pytest.raises(ConfigError):
            cfg.set("limit.max_read_depth", -3)
        assert cfg.max_read_depth() == 5  # untouched after rejection

    def test_immutable_keys_still_enforced(self):
        cfg = Config({"dsn": "memory"})
        with pytest.raises(ConfigError):
            cfg.set("dsn", "columnar")

    def test_valid_config_passes(self):
        Config({
            "dsn": "memory",
            "check": {"engine": "tpu", "frontier_cap": 4096},
            "serve": {"read": {"host": "127.0.0.1", "port": 0}},
            "tracing": {"enabled": True, "provider": "memory"},
            "tenancy": {"header": "x-keto-network"},
        })

    def test_slow_query_threshold_validates(self):
        Config({"log": {"slow_query_ms": 10.5}})
        with pytest.raises(ConfigError):
            Config({"log": {"slow_query_ms": -1}})


class TestTraceContext:
    def test_parse_roundtrip(self):
        from keto_tpu.observability import new_trace, parse_traceparent

        ctx = new_trace()
        back = parse_traceparent(ctx.to_traceparent())
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id
        assert back.sampled is True

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-abc-def-01",
        "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",  # forbidden version
        "00-" + "0" * 32 + "-" + "b" * 16 + "-01",  # zero trace id
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # zero span id
        "00-" + "z" * 32 + "-" + "b" * 16 + "-01",  # non-hex
    ])
    def test_malformed_is_none(self, bad):
        from keto_tpu.observability import parse_traceparent

        assert parse_traceparent(bad) is None

    def test_child_keeps_trace_id(self):
        from keto_tpu.observability import new_trace

        ctx = new_trace()
        child = ctx.child()
        assert child.trace_id == ctx.trace_id
        assert child.span_id != ctx.span_id


class TestTracing:
    def test_spans_cover_store_engine_and_rpc(self):
        cfg = Config({
            "dsn": "memory",
            "check": {"engine": "tpu"},
            "tracing": {"enabled": True, "provider": "memory"},
            "serve": {
                "read": {"host": "127.0.0.1", "port": 0},
                "write": {"host": "127.0.0.1", "port": 0},
                "metrics": {"host": "127.0.0.1", "port": 0},
            },
        })
        cfg.set_namespaces([Namespace(name="files")])
        reg = Registry(cfg)
        reg.relation_tuple_manager().write_relation_tuples(
            [RelationTuple.from_string("files:doc#owner@alice")]
        )
        d = Daemon(reg)
        d.start()
        try:
            u = (
                f"http://127.0.0.1:{d.read_port}/relation-tuples/check/openapi"
                "?namespace=files&object=doc&relation=owner&subject_id=alice"
            )
            assert json.load(urllib.request.urlopen(u))["allowed"] is True
        finally:
            d.stop()
        names = reg.tracer().span_names()
        # store op, snapshot build, kernel launch, result resolution, and
        # the HTTP request span must all be present
        assert "persistence.write_relation_tuples" in names
        assert "engine.snapshot_build" in names
        assert "engine.kernel_launch" in names
        assert "engine.resolve_batch" in names
        assert any(n.startswith("http.") for n in names)

    def test_tracing_disabled_is_noop(self):
        cfg = Config({"dsn": "memory"})
        cfg.set_namespaces([Namespace(name="files")])
        reg = Registry(cfg)
        t = reg.tracer()
        with t.span("anything") as s:
            s.set_attribute("k", "v")
        assert not hasattr(t, "spans")
        assert t.active is False


# ---------------------------------------------------------------------------
# the request-scoped telemetry plane (PR 3 tentpole)
# ---------------------------------------------------------------------------

NAMESPACES = [Namespace(name="files")]
TUPLE = "files:doc#owner@alice"

# engine stages a device-served single check must attribute (the
# acceptance bar: >= 3 engine stages sharing the request's trace_id)
ENGINE_STAGES = {"engine.assemble", "engine.dispatch", "engine.device_wait"}


@pytest.fixture(scope="module")
def daemon():
    cfg = Config({
        "dsn": "memory",
        # cache off: this module asserts the batcher/engine pipeline
        # internals (queue/assemble/dispatch spans, stage histograms) on
        # repeated identical checks — with the serve-side check cache on,
        # repeats would (correctly) skip the pipeline under test
        "check": {"engine": "tpu", "cache": {"enabled": False}},
        "tracing": {"enabled": True, "provider": "memory"},
        "serve": {
            "read": {
                "host": "127.0.0.1", "port": 0,
                # direct aio listener beside the muxed (threaded) port:
                # one daemon exercises all three planes
                "grpc": {"host": "127.0.0.1", "port": 0, "aio": True},
            },
            "write": {"host": "127.0.0.1", "port": 0},
            "metrics": {"host": "127.0.0.1", "port": 0},
        },
    })
    cfg.set_namespaces(NAMESPACES)
    reg = Registry(cfg)
    reg.relation_tuple_manager().write_relation_tuples(
        [RelationTuple.from_string(TUPLE)]
    )
    d = Daemon(reg)
    d.start()
    yield d
    d.stop()


def _span_names_for(reg, trace_id: str) -> set:
    return {s.name for s in reg.tracer().spans_for_trace(trace_id)}


def _assert_full_pipeline(names: set, transport_prefix: str):
    assert any(n.startswith(transport_prefix) for n in names), names
    assert "batcher.queue" in names, names
    assert ENGINE_STAGES <= names, names


class TestTraceparentParity:
    """One Check with a traceparent yields correlated spans for the
    transport, the batcher queue, and >= 3 engine stages — identically
    through REST, threaded gRPC, and the aio plane."""

    def test_rest_header(self, daemon):
        tid = "11" * 16
        req = urllib.request.Request(
            f"http://127.0.0.1:{daemon.read_port}"
            "/relation-tuples/check/openapi"
            "?namespace=files&object=doc&relation=owner&subject_id=alice",
            headers={"traceparent": f"00-{tid}-{'22' * 8}-01"},
        )
        assert json.load(urllib.request.urlopen(req))["allowed"] is True
        _assert_full_pipeline(
            _span_names_for(daemon.registry, tid), "http."
        )

    def test_grpc_metadata(self, daemon):
        tid = "33" * 16
        client = ReadClient(open_channel(f"127.0.0.1:{daemon.read_port}"))
        try:
            assert client.check(
                RelationTuple.from_string(TUPLE),
                traceparent=f"00-{tid}-{'44' * 8}-01",
            ) is True
        finally:
            client.close()
        _assert_full_pipeline(
            _span_names_for(daemon.registry, tid), "grpc."
        )

    def test_aio_metadata(self, daemon):
        tid = "55" * 16
        client = ReadClient(
            open_channel(f"127.0.0.1:{daemon.read_grpc_port}")
        )
        try:
            assert client.check(
                RelationTuple.from_string(TUPLE),
                traceparent=f"00-{tid}-{'66' * 8}-01",
            ) is True
        finally:
            client.close()
        _assert_full_pipeline(
            _span_names_for(daemon.registry, tid), "grpc."
        )

    def test_malformed_header_starts_fresh_trace(self, daemon):
        req = urllib.request.Request(
            f"http://127.0.0.1:{daemon.read_port}"
            "/relation-tuples/check/openapi"
            "?namespace=files&object=doc&relation=owner&subject_id=alice",
            headers={"traceparent": "not-a-traceparent"},
        )
        assert json.load(urllib.request.urlopen(req))["allowed"] is True


class TestStageMetrics:
    def test_stage_histograms_in_prometheus_export(self, daemon):
        # a served check has already run (TestTraceparentParity order is
        # not guaranteed — serve one more to be self-sufficient)
        client = ReadClient(open_channel(f"127.0.0.1:{daemon.read_port}"))
        try:
            client.check(RelationTuple.from_string(TUPLE))
        finally:
            client.close()
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{daemon.metrics_port}/metrics/prometheus"
        ).read().decode()
        for stage in ("transport", "queue", "assemble", "dispatch",
                      "device_wait"):
            needle = (
                'keto_tpu_check_stage_duration_seconds_count'
                f'{{stage="{stage}"}}'
            )
            assert needle in text, f"missing stage sample: {stage}"
        # the new pipeline gauges export too
        for gauge in (
            "keto_tpu_batcher_queue_depth", "keto_tpu_inflight_launches",
            "keto_tpu_batch_occupancy", "keto_tpu_snapshot_hbm_bytes",
            "keto_tpu_delta_overlay_ops",
            "keto_tpu_compaction_lag_versions",
        ):
            assert gauge in text, f"missing gauge: {gauge}"

    def test_snapshot_hbm_bytes_nonzero(self, daemon):
        m = daemon.registry.metrics()
        assert m.snapshot_hbm_bytes._value.get() > 0

    def test_error_status_mirrored_into_request_counter(self, daemon):
        # bare check route mirrors deny as 403 — the outcome label must
        # say 403, not OK (the satellite fix: no error response counts
        # as code="OK")
        url = (
            f"http://127.0.0.1:{daemon.read_port}/relation-tuples/check"
            "?namespace=files&object=doc&relation=owner&subject_id=nobody"
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url)
        assert e.value.code == 403
        # the counter increments when the server's observe_request block
        # EXITS, which races the client seeing the response bytes — poll
        # the scrape briefly instead of asserting the first read (the
        # same post-response race PR 4 de-flaked on the request log)
        deadline = time.monotonic() + 5
        text = ""
        while time.monotonic() < deadline:
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{daemon.metrics_port}/metrics/prometheus"
            ).read().decode()
            if 'code="403"' in text:
                break
            time.sleep(0.05)
        assert 'code="403"' in text


class TestRequestAndSlowQueryLogs:
    def test_request_log_wired_into_transports(self, daemon, caplog):
        with caplog.at_level(logging.INFO, logger="keto_tpu"):
            client = ReadClient(
                open_channel(f"127.0.0.1:{daemon.read_port}")
            )
            try:
                client.check(RelationTuple.from_string(TUPLE))
            finally:
                client.close()
            urllib.request.urlopen(
                f"http://127.0.0.1:{daemon.read_port}"
                "/relation-tuples/check/openapi"
                "?namespace=files&object=doc&relation=owner&subject_id=alice"
            )
            # the REST plane logs AFTER the response bytes reach the
            # client — wait (inside the raised-level block, or the late
            # record is filtered at WARNING) for the handler thread
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if {
                    getattr(r, "transport", None)
                    for r in caplog.records
                    if r.getMessage() == "request handled"
                } >= {"grpc", "http"}:
                    break
                time.sleep(0.01)
        handled = [
            r for r in caplog.records if r.getMessage() == "request handled"
        ]
        transports = {getattr(r, "transport", None) for r in handled}
        assert "grpc" in transports and "http" in transports
        for r in handled:
            if getattr(r, "method", "") in ("Check",):
                assert getattr(r, "trace_id", "")
                assert "queue" in getattr(r, "stages_ms", {})

    def test_slow_query_log_fires_above_threshold(self, daemon, caplog):
        daemon.registry.config.set("log.slow_query_ms", 0)
        try:
            with caplog.at_level(logging.WARNING, logger="keto_tpu"):
                client = ReadClient(
                    open_channel(f"127.0.0.1:{daemon.read_port}")
                )
                try:
                    client.check(RelationTuple.from_string(TUPLE))
                finally:
                    client.close()
            slow = [
                r for r in caplog.records
                if r.getMessage().startswith("slow request")
            ]
            assert slow, "threshold 0 must fire on every request"
            msg = slow[0].getMessage()
            assert "trace_id=" in msg and "stages_ms=" in msg
        finally:
            daemon.registry.config.set("log.slow_query_ms", None)

    def test_slow_query_log_silent_below_threshold(self, daemon, caplog):
        daemon.registry.config.set("log.slow_query_ms", 60_000.0)
        try:
            with caplog.at_level(logging.WARNING, logger="keto_tpu"):
                client = ReadClient(
                    open_channel(f"127.0.0.1:{daemon.read_port}")
                )
                try:
                    client.check(RelationTuple.from_string(TUPLE))
                finally:
                    client.close()
            assert not any(
                r.getMessage().startswith("slow request")
                for r in caplog.records
            )
        finally:
            daemon.registry.config.set("log.slow_query_ms", None)


class TestProfilerEndpoint:
    def _post(self, daemon, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{daemon.metrics_port}{path}",
            data=json.dumps(body).encode() if body is not None else b"",
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        return json.load(urllib.request.urlopen(req))

    def test_live_cycle_writes_artifact(self, daemon, tmp_path):
        out = str(tmp_path / "serve.pstats")
        started = self._post(
            daemon, "/admin/profiling", {"mode": "cpu", "path": out}
        )
        assert started["running"] is True and started["mode"] == "cpu"
        # capture real serve work without restarting the daemon
        client = ReadClient(open_channel(f"127.0.0.1:{daemon.read_port}"))
        try:
            client.check(RelationTuple.from_string(TUPLE))
        finally:
            client.close()
        stopped = self._post(daemon, "/admin/profiling/stop")
        assert stopped["artifact"] == out
        assert (tmp_path / "serve.pstats").exists()
        # pstats must actually load (a truncated dump would too-late-fail
        # the operator)
        import pstats

        pstats.Stats(out)

    def test_double_stop_is_idempotent(self, daemon):
        first = self._post(daemon, "/admin/profiling/stop")
        second = self._post(daemon, "/admin/profiling/stop")
        assert second == {"running": False, "artifact": None}
        assert first["running"] is False

    def test_double_start_conflicts(self, daemon, tmp_path):
        self._post(
            daemon, "/admin/profiling",
            {"mode": "mem", "path": str(tmp_path / "m.txt")},
        )
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                self._post(daemon, "/admin/profiling", {"mode": "cpu"})
            assert e.value.code == 409
        finally:
            self._post(daemon, "/admin/profiling/stop")

    def test_unknown_mode_is_400(self, daemon):
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(daemon, "/admin/profiling", {"mode": "gpu"})
        assert e.value.code == 400

    def test_path_escaping_profile_dir_is_400(self, daemon):
        # the admin endpoint must not be an arbitrary-file-write
        # primitive: artifact paths are confined to KETO_PROFILE_DIR
        # (default: the system tempdir)
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(
                daemon, "/admin/profiling",
                {"mode": "cpu", "path": "/etc/keto-pwned"},
            )
        assert e.value.code == 400
        # traversal out of the base dir is caught after normalization
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(
                daemon, "/admin/profiling",
                {"mode": "cpu", "path": "../../etc/keto-pwned"},
            )
        assert e.value.code == 400

    def test_status_reports_idle(self, daemon):
        status = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{daemon.metrics_port}/admin/profiling"
        ))
        assert status["running"] is False


class TestTracedManagerCoverage:
    """Every public store-manager method is either span-traced or
    explicitly exempted — the PR-2 watch ops bypassed the proxy because
    nothing enforced the list; this does."""

    def _public_methods(self, cls) -> set:
        import inspect

        return {
            name
            for name, member in inspect.getmembers(
                cls, predicate=inspect.isfunction
            )
            if not name.startswith("_")
        }

    @pytest.mark.parametrize("cls_path", [
        ("keto_tpu.storage.memory", "MemoryManager"),
        ("keto_tpu.storage.sqlite", "SQLPersister"),
        ("keto_tpu.storage.columnar", "ColumnarStore"),
    ])
    def test_every_public_method_covered(self, cls_path):
        import importlib

        from keto_tpu.observability import TracedManager

        mod, cls_name = cls_path
        cls = getattr(importlib.import_module(mod), cls_name)
        covered = set(TracedManager._TRACED) | set(TracedManager._EXEMPT)
        missing = self._public_methods(cls) - covered
        assert not missing, (
            f"{cls_name} public methods neither traced nor exempted: "
            f"{sorted(missing)} — add to TracedManager._TRACED or "
            f"_EXEMPT (with the reason)"
        )

    def test_traced_and_exempt_disjoint(self):
        from keto_tpu.observability import TracedManager

        both = set(TracedManager._TRACED) & set(TracedManager._EXEMPT)
        assert not both

    def test_traced_names_exist_somewhere(self):
        # a stale _TRACED entry (renamed store op) would silently trace
        # nothing; every name must exist on at least one store class
        import importlib

        from keto_tpu.observability import TracedManager

        classes = [
            getattr(importlib.import_module(m), c)
            for m, c in (
                ("keto_tpu.storage.memory", "MemoryManager"),
                ("keto_tpu.storage.sqlite", "SQLPersister"),
                ("keto_tpu.storage.columnar", "ColumnarStore"),
            )
        ]
        for name in TracedManager._TRACED:
            assert any(hasattr(cls, name) for cls in classes), (
                f"_TRACED entry {name!r} matches no store class method"
            )

    def test_watch_era_ops_are_traced(self):
        from keto_tpu.observability import RecordingTracer, TracedManager
        from keto_tpu.storage.memory import MemoryManager

        tracer = RecordingTracer()
        mgr = TracedManager(MemoryManager(), tracer)
        mgr.write_relation_tuples([RelationTuple.from_string(TUPLE)])
        mgr.changes_since(0)
        mgr.changelog_since(0)
        names = tracer.span_names()
        assert "persistence.changes_since" in names
        assert "persistence.changelog_since" in names


class TestMetricsDocsGolden:
    def test_docs_table_in_sync(self):
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "check_metrics_docs.py")],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# the explain/export plane (§5m): OTLP span exporter, exemplars,
# request-log sampling, flight-recorder filters
# ---------------------------------------------------------------------------


class _StubCollector:
    """Stdlib OTLP collector stand-in: records every POSTed JSON body."""

    def __init__(self):
        import threading
        from http.server import BaseHTTPRequestHandler, HTTPServer

        received = self.received = []

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                received.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, *a):
                pass

        self.srv = HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    @property
    def endpoint(self):
        return f"http://127.0.0.1:{self.srv.server_address[1]}/v1/traces"

    def spans(self):
        out = []
        for payload in self.received:
            for rs in payload.get("resourceSpans", ()):
                for ss in rs.get("scopeSpans", ()):
                    out.extend(ss.get("spans", ()))
        return out

    def close(self):
        self.srv.shutdown()
        self.srv.server_close()


class TestSpanExporter:
    def _tracer(self, exporter):
        from keto_tpu.observability import RecordingTracer

        return RecordingTracer(exporter=exporter)

    def test_exports_wellformed_parent_linked_spans(self):
        from keto_tpu.observability import SpanExporter, new_trace

        collector = _StubCollector()
        exp = SpanExporter(collector.endpoint, flush_interval_s=0.02)
        try:
            tracer = self._tracer(exp)
            ctx = new_trace().child()  # like a transport ingesting one
            with tracer.span("http.test", ctx=ctx, root=True):
                pass
            tracer.record(
                "engine.device_wait", ctx=ctx, duration_s=0.003,
                launch_id=41,
            )
            assert exp.flush(5.0)
            spans = collector.spans()
            by_name = {s["name"]: s for s in spans}
            assert set(by_name) == {"http.test", "engine.device_wait"}
            root = by_name["http.test"]
            child = by_name["engine.device_wait"]
            assert root["traceId"] == child["traceId"] == ctx.trace_id
            # the root takes the ctx's own span id; the child parents
            # to it; the root parents to the ORIGINAL caller span
            assert root["spanId"] == ctx.span_id
            assert child["parentSpanId"] == ctx.span_id
            assert root["parentSpanId"] == ctx.parent_span_id
            # launch ids ride as span events (the flightrec join)
            ev = child["events"][0]
            assert ev["name"] == "flightrec.launch"
            assert ev["attributes"][0]["value"]["intValue"] == "41"
            # timestamps are real epoch nanos, end >= start
            assert int(child["endTimeUnixNano"]) >= int(
                child["startTimeUnixNano"]
            )
            assert exp.stats["exported"] == 2
        finally:
            exp.close()
            collector.close()

    def test_queue_overflow_drops_counted_never_blocks(self):
        from keto_tpu.observability import (
            RecordedSpan,
            SpanExporter,
        )

        # unroutable endpoint + tiny queue: every POST fails, overflow
        # drops count, and enqueue stays non-blocking throughout
        exp = SpanExporter(
            "http://127.0.0.1:9/v1/traces", queue_size=2,
            flush_interval_s=30.0, post_timeout_s=0.2,
        )
        try:
            t0 = time.perf_counter()
            results = [
                exp.enqueue(RecordedSpan("s", {
                    "trace_id": "ab" * 16, "span_id": "cd" * 8,
                    "t_mono": time.monotonic(),
                }))
                for _ in range(10)
            ]
            took = time.perf_counter() - t0
            assert took < 0.5, "enqueue must never block"
            assert results.count(False) >= 8  # queue bound 2
            assert exp.stats["dropped_queue_full"] >= 8
        finally:
            exp.close(timeout=0.1)

    def test_post_error_drops_counted(self):
        from keto_tpu.observability import RecordedSpan, SpanExporter

        exp = SpanExporter(
            "http://127.0.0.1:9/v1/traces", flush_interval_s=0.02,
            post_timeout_s=0.2,
        )
        try:
            exp.enqueue(RecordedSpan("s", {
                "trace_id": "ab" * 16, "span_id": "cd" * 8,
                "t_mono": time.monotonic(),
            }))
            assert exp.flush(5.0)
            assert exp.stats["dropped_post_error"] == 1
            assert exp.stats["exported"] == 0
        finally:
            exp.close(timeout=0.1)

    def test_endpoint_config_builds_exporting_tracer(self):
        from keto_tpu.observability import RecordingTracer

        collector = _StubCollector()
        try:
            cfg = Config({
                "dsn": "memory",
                "observability": {"otlp": {"endpoint": collector.endpoint}},
            })
            reg = Registry(cfg)
            tracer = reg.tracer()
            assert isinstance(tracer, RecordingTracer)
            assert tracer.exporter is reg.span_exporter()
            reg.span_exporter().close(timeout=0.5)
        finally:
            collector.close()

    def test_no_endpoint_no_exporter(self):
        reg = Registry(Config({"dsn": "memory"}))
        assert reg.span_exporter() is None


class TestExemplars:
    def test_stage_histogram_carries_trace_exemplar(self, daemon):
        from keto_tpu.observability import new_trace

        ctx = new_trace()
        client = ReadClient(open_channel(f"127.0.0.1:{daemon.read_port}"))
        try:
            client.check(
                RelationTuple.from_string(TUPLE),
                traceparent=ctx.to_traceparent(),
            )
        finally:
            client.close()
        req = urllib.request.Request(
            f"http://127.0.0.1:{daemon.metrics_port}/metrics/prometheus",
            headers={"Accept": "application/openmetrics-text"},
        )
        with urllib.request.urlopen(req) as r:
            assert "openmetrics" in r.headers["Content-Type"]
            text = r.read().decode()
        exemplar_lines = [
            line for line in text.splitlines()
            if "keto_tpu_check_stage_duration_seconds_bucket" in line
            and "# {" in line and "trace_id=" in line
        ]
        assert exemplar_lines, "stage buckets must carry trace exemplars"
        # the classic exposition stays the default (no exemplars there)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{daemon.metrics_port}/metrics/prometheus"
        ) as r:
            classic = r.read().decode()
        assert "# {" not in classic


class TestRequestLogSampling:
    def _one_check(self, daemon):
        client = ReadClient(open_channel(f"127.0.0.1:{daemon.read_port}"))
        try:
            client.check(RelationTuple.from_string(TUPLE))
        finally:
            client.close()

    def test_default_rate_is_one_every_request_logged(self, daemon, caplog):
        # schema default 1.0 pinned: with the key unset, the INFO line
        # emits unconditionally (exactly the pre-sampling behavior)
        assert daemon.registry.config.get("log.request_sample_rate") is None
        with caplog.at_level(logging.INFO, logger="keto_tpu"):
            self._one_check(daemon)
        assert any(
            r.getMessage() == "request handled" for r in caplog.records
        )

    def test_rate_zero_suppresses_info_keeps_slow_warning(
        self, daemon, caplog
    ):
        daemon.registry.config.set("log.request_sample_rate", 0.0)
        daemon.registry.config.set("log.slow_query_ms", 0)
        try:
            with caplog.at_level(logging.INFO, logger="keto_tpu"):
                self._one_check(daemon)
            assert not any(
                r.getMessage() == "request handled"
                and getattr(r, "transport", "") == "grpc"
                for r in caplog.records
            )
            # the slow-query WARNING always emits — sampling must never
            # swallow incident evidence
            assert any(
                r.getMessage().startswith("slow request")
                for r in caplog.records
            )
        finally:
            daemon.registry.config.set("log.request_sample_rate", 1.0)
            daemon.registry.config.set("log.slow_query_ms", None)

    def test_rate_validates_in_schema(self):
        Config({"log": {"request_sample_rate": 0.25}})
        with pytest.raises(ConfigError):
            Config({"log": {"request_sample_rate": 1.5}})


class TestFlightrecFilters:
    def _dump(self, daemon, query=""):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{daemon.metrics_port}/admin/flightrec{query}"
        ) as r:
            return json.loads(r.read())

    def test_kind_and_trace_id_filters(self, daemon):
        from keto_tpu.observability import new_trace

        ctx = new_trace()
        client = ReadClient(open_channel(f"127.0.0.1:{daemon.read_port}"))
        try:
            client.check(
                RelationTuple.from_string(TUPLE),
                traceparent=ctx.to_traceparent(),
            )
            client.check(RelationTuple.from_string(TUPLE))
        finally:
            client.close()
        full = self._dump(daemon)
        assert full["entries"], "ring must hold the check launches"
        by_kind = self._dump(daemon, "?kind=check")
        assert by_kind["entries"]
        assert all(e["kind"] == "check" for e in by_kind["entries"])
        none_kind = self._dump(daemon, "?kind=filter")
        assert none_kind["entries"] == []
        by_trace = self._dump(daemon, f"?trace_id={ctx.trace_id}")
        assert by_trace["entries"], "trace filter must find the ride"
        assert all(
            ctx.trace_id in e["trace_ids"] for e in by_trace["entries"]
        )
        # filters compose
        both = self._dump(daemon, f"?kind=check&trace_id={ctx.trace_id}")
        assert {e["launch_id"] for e in both["entries"]} == {
            e["launch_id"] for e in by_trace["entries"]
        }

    def test_since_launch_id_cursor(self, daemon):
        client = ReadClient(open_channel(f"127.0.0.1:{daemon.read_port}"))
        try:
            client.check(RelationTuple.from_string(TUPLE))
        finally:
            client.close()
        full = self._dump(daemon)
        ids = [e["launch_id"] for e in full["entries"]]
        assert ids == sorted(ids), "dump must be in launch-id order"
        cursor = ids[len(ids) // 2]
        tail = self._dump(daemon, f"?since_launch_id={cursor}")
        # STRICTLY-greater semantics: the poller passes the max id it
        # has seen and receives only the increment
        assert [e["launch_id"] for e in tail["entries"]] == [
            i for i in ids if i > cursor
        ]
        # a cursor at the ring's tail yields the empty increment
        empty = self._dump(daemon, f"?since_launch_id={max(ids)}")
        assert empty["entries"] == []
        # composes with ?kind=
        both = self._dump(daemon, f"?kind=check&since_launch_id={cursor}")
        assert all(
            e["kind"] == "check" and e["launch_id"] > cursor
            for e in both["entries"]
        )
        # a non-integer cursor is typed client error, not a 500
        with pytest.raises(urllib.error.HTTPError) as e:
            self._dump(daemon, "?since_launch_id=abc")
        assert e.value.code == 400
