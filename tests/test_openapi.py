"""Served OpenAPI spec (VERDICT r2 item 9): the document is generated
from the router's route constants and served at /.well-known/openapi.json
on the read and write routers; REAL response payloads from the live
daemon must validate against the spec's schemas."""

import json
import urllib.error
import urllib.request

import jsonschema
import pytest

from keto_tpu.api.daemon import Daemon
from keto_tpu.api.rest_server import SPEC_ROUTE
from keto_tpu.config import Config
from keto_tpu.ketoapi import RelationTuple
from keto_tpu.namespace import Namespace
from keto_tpu.registry import Registry


@pytest.fixture(scope="module")
def daemon():
    cfg = Config({
        "dsn": "memory",
        "serve": {
            "read": {"host": "127.0.0.1", "port": 0},
            "write": {"host": "127.0.0.1", "port": 0},
            "metrics": {"host": "127.0.0.1", "port": 0},
        },
    })
    cfg.set_namespaces([Namespace(name="files")])
    reg = Registry(cfg)
    reg.relation_tuple_manager().write_relation_tuples([
        RelationTuple.from_string("files:doc#owner@alice"),
        RelationTuple.from_string("files:doc#viewer@(files:doc#owner)"),
    ])
    d = Daemon(reg)
    d.start()
    yield d
    d.stop()


def _get(port, path):
    return urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=30)


def _schema_for(spec, path, method, code):
    resp = spec["paths"][path][method]["responses"][str(code)]
    schema = dict(resp["content"]["application/json"]["schema"])
    # resolve against the full component set
    schema["components"] = spec["components"]
    return schema


class TestServedSpec:
    def test_spec_served_on_read_and_write(self, daemon):
        """Each port's spec advertises only routes THAT port answers."""
        read = json.load(_get(daemon.read_port, SPEC_ROUTE))
        write = json.load(_get(daemon.write_port, SPEC_ROUTE))
        assert read["openapi"].startswith("3.")
        assert "/relation-tuples/check" in read["paths"]
        assert "/admin/relation-tuples" not in read["paths"]
        assert "/admin/relation-tuples" in write["paths"]
        assert "/relation-tuples/check" not in write["paths"]

    def test_spec_routes_match_router_constants(self, daemon):
        from keto_tpu.api import rest_server as r

        read = json.load(_get(daemon.read_port, SPEC_ROUTE))
        write = json.load(_get(daemon.write_port, SPEC_ROUTE))
        for route in (
            r.READ_ROUTE_BASE, r.CHECK_ROUTE_BASE, r.CHECK_OPENAPI_ROUTE,
            r.EXPAND_ROUTE, r.ALIVE_PATH, r.READY_PATH, r.VERSION_PATH,
        ):
            assert route in read["paths"], route
        for route in (
            r.WRITE_ROUTE_BASE, r.ALIVE_PATH, r.READY_PATH, r.VERSION_PATH,
        ):
            assert route in write["paths"], route

    @pytest.mark.parametrize("path,method,code,live", [
        ("/relation-tuples/check/openapi", "get",
         200, "/relation-tuples/check/openapi?namespace=files&object=doc"
              "&relation=owner&subject_id=alice"),
        ("/relation-tuples", "get",
         200, "/relation-tuples?namespace=files"),
        ("/relation-tuples/expand", "get",
         200, "/relation-tuples/expand?namespace=files&object=doc"
              "&relation=viewer&max-depth=3"),
        ("/version", "get", 200, "/version"),
        ("/health/alive", "get", 200, "/health/alive"),
    ])
    def test_live_payloads_validate(self, daemon, path, method, code, live):
        spec = json.load(_get(daemon.read_port, SPEC_ROUTE))
        payload = json.load(_get(daemon.read_port, live))
        schema = _schema_for(spec, path, method, code)
        jsonschema.Draft7Validator(schema).validate(payload)

    def test_error_payload_validates(self, daemon):
        spec = json.load(_get(daemon.read_port, SPEC_ROUTE))
        try:
            _get(daemon.read_port, "/relation-tuples?namespace=absent")
            payload = None
        except urllib.error.HTTPError as e:
            payload = json.load(e)
        assert payload is not None
        schema = _schema_for(spec, "/relation-tuples", "get", 404)
        jsonschema.Draft7Validator(schema).validate(payload)
