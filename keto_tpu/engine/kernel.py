"""Batched BFS check kernel (single device) + shared step phases.

The TPU replacement for the reference's goroutine-per-branch recursive
walk (internal/check/engine.go:183-207 + checkgroup): all branches of all
in-flight checks advance together as one frontier of tasks
(query, object-slot, relation, remaining-depth), inside one
`jax.lax.while_loop` with static shapes:

  per step:
    1. flag tasks whose (ns, rel) program needs host evaluation (AND/NOT
       islands, missing relation config — engine.go:219-228)
    2. direct-probe every task against the edge hash table (the batched
       analog of checkDirect's single-row SELECT) and OR hits into the
       per-query member mask (short-circuit = per-query done-mask)
    3. expand every task: subject-set CSR row (checkExpandSubject), plus
       its compiled rewrite instructions (COMPUTED relation swap at the
       SAME depth, rewrites.go:161-193; TTU row traversal at depth-1,
       rewrites.go:195-260); expansion counts → exclusive scan →
       vectorized segmented gather into the next frontier
    4. dedupe the next frontier on (query, object, relation) keeping the
       deepest remaining-depth instance (safe: more depth explores more)

TPU-specific gather discipline (measured, tools/microbench2.py): a
row-gather from a 2-D table moves its whole row for roughly the cost of
one element (~15ns/row on v5e), while N per-column gathers pay N times.
So every hash table lives on device as PACKED interleaved rows —
[cap, 8] for the 5-key edge tables, [cap, 4] for (obj, rel)->value —
and each logical lookup is ONE [F, P, row]-shaped row-gather, fenced
with optimization_barrier so XLA emits its fast standalone gather
kernel instead of scalarizing it inside a fusion. All probe rounds/
slots batch into one wide trailing index dim per lookup.

The phases are factored as standalone functions so the sharded multi-chip
kernel (keto_tpu/parallel/kernel.py) can interleave them with mesh
collectives: probe hits are psum-OR-merged across edge shards and local
expansions are all-gathered before the shared dedupe.

Depth bookkeeping matches the reference exactly: direct probes need
depth ≥ 1 (restDepth-1 ≥ 0), expand-subject and TTU children are enqueued
at depth-1 (only when ≥ 0), computed children keep their depth.

Tasks touching host-only programs (AND/NOT islands), config-missing
relations, delta-dirty rows, or overflowing the frontier raise the
per-query needs_host flag; the engine facade re-runs those queries on the
exact host engine.

All arrays int32/uint32/bool — no 64-bit emulation on TPU.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .delta import DELTA_PROBES, DIRTY_FOR_CHECK, empty_delta_tables
from .snapshot import (
    EMPTY,
    FLAG_CONFIG_MISSING,
    FLAG_HOST_ONLY,
    FLAG_ISLAND,
    INSTR_COMPUTED,
    INSTR_NONE,
    INSTR_TTU,
    GraphSnapshot,
    slots_per_bucket,
)

_GOLDEN = jnp.uint32(0x9E3779B9)

# Host-replay cause codes, priority-ordered (VERDICT r2 item 7: a host
# fallback because of an AND/NOT cap must be distinguishable from one
# because of an error). Each flag site scatter-maxes its code into the
# per-query needs_host array — the SAME single scatter per site as the
# old boolean scheme, so observability costs no extra device work. A
# query flagged for several reasons reports the highest code (more
# specific/semantic causes outrank capacity ones).
CAUSE_NONE = 0
CAUSE_STEP_EXHAUSTED = 1  # step budget ran out with live tasks
CAUSE_FRONTIER_OVERFLOW = 2  # expansion truncated / dedupe survivors > F
CAUSE_ISLAND_OVERFLOW = 3  # island instance table full (island_cap)
CAUSE_DIRTY = 4  # delta-dirty CSR row (stale compacted data)
CAUSE_REL_NOT_FOUND = 5  # relation missing from a configured namespace
CAUSE_CONFIG_MISSING = 6  # FLAG_CONFIG_MISSING program
CAUSE_REWRITE_CAP = 7  # FLAG_HOST_ONLY: rewrite exceeds instr/circuit caps
CAUSE_ISLAND_HOST = 8  # AND/NOT program, kernel compiled without islands

CAUSE_NAMES = {
    CAUSE_STEP_EXHAUSTED: "step_exhausted",
    CAUSE_FRONTIER_OVERFLOW: "frontier_overflow",
    CAUSE_ISLAND_OVERFLOW: "island_overflow",
    CAUSE_DIRTY: "dirty_row",
    CAUSE_REL_NOT_FOUND: "relation_not_found",
    CAUSE_CONFIG_MISSING: "config_missing",
    CAUSE_REWRITE_CAP: "rewrite_cap",
    CAUSE_ISLAND_HOST: "island_host",
}
# host-side-only cause (query vocabulary never reached the device)
CAUSE_NAME_UNINDEXED = "unindexed"

# -- launch introspection counters ---------------------------------------------
# Every BFS kernel (check, sharded check, expand, reverse) accumulates a
# small int32 stats vector inside its bounded loop and appends it to the
# packed result, so the counters ride the batch's EXISTING resolve-phase
# readback — zero extra host syncs (ketolint's host-sync pass still sees
# exactly one annotated sync point per batch). Slot layout is shared so
# the flight recorder (observability.FlightRecorder) and the bench
# summaries can treat every launch kind uniformly; kernels that have no
# value for a slot leave it zero.
N_LAUNCH_STATS = 8
STAT_STEPS = 0          # loop iterations actually executed (vs the cap)
STAT_FRONTIER_SUM = 1   # sum of n_tasks over executed steps (task-steps)
STAT_FRONTIER_MAX = 2   # max n_tasks over executed steps
STAT_LIVE_SUM = 3       # sum of genuinely-live tasks (excludes bucket
                        # padding: seeded invalid queries sit at depth -1)
STAT_PROBE_HITS = 4     # direct-edge probe hits accumulated (check only)
STAT_EDGE_ROWS = 5      # candidate rows materially gathered (valid
                        # expansion children / emitted expand edges)
STAT_DEDUPE_KEPT = 6    # dedupe survivors admitted to the next frontier
STAT_RESERVED = 7

STAT_NAMES = (
    "steps", "frontier_sum", "frontier_max", "live_sum",
    "probe_hits", "edge_rows", "dedupe_kept", "reserved",
)


def empty_launch_stats():
    return jnp.zeros(N_LAUNCH_STATS, dtype=jnp.int32)


def update_launch_stats(
    stats: jnp.ndarray,
    n_tasks: jnp.ndarray,
    n_live: jnp.ndarray,
    n_hits: jnp.ndarray,
    n_children: jnp.ndarray,
    n_kept: jnp.ndarray,
) -> jnp.ndarray:
    """One step's counter accumulation (shared by the single-device and
    sharded check kernels so both report identical semantics). All
    operands must be REPLICATED values on a mesh — the sharded caller
    passes post-collective quantities only."""
    inc = jnp.stack([
        jnp.int32(1),
        n_tasks.astype(jnp.int32),
        jnp.int32(0),
        n_live.astype(jnp.int32),
        n_hits.astype(jnp.int32),
        n_children.astype(jnp.int32),
        n_kept.astype(jnp.int32),
        jnp.int32(0),
    ])
    return (stats + inc).at[STAT_FRONTIER_MAX].max(n_tasks.astype(jnp.int32))


def launch_stats_dict(stats) -> dict:
    """Host-side view of a stats vector as named fields (entry payload
    for the flight recorder and the bench aggregates)."""
    vals = [int(v) for v in stats]
    return {
        name: vals[i]
        for i, name in enumerate(STAT_NAMES)
        if name != "reserved"
    }


def estimate_step_gather_bytes(cfg: dict) -> int:
    """Estimated bytes the check kernel's gather sites move in ONE BFS
    step, from the launch's static config. The hot gathers are DENSE over
    the frontier cap (padding rows gather like live ones — that is the
    measured cost model, tools/microbench_gather_layout.py: one bucket
    row = one 256 B gather regardless of occupancy), so the estimate is
    exact up to XLA fusion choices and scales with frontier_cap and the
    probe depths, which themselves grow with table load. Multiply by
    STAT_STEPS for a launch's total; the resolve path records it in the
    flight-recorder entry."""
    F = int(cfg["frontier_cap"])
    K = int(cfg["K"])
    S = K + 1
    has_delta = bool(cfg.get("has_delta", True))
    bucket_row = 256  # every bucket is one 256 B gather row (snapshot.py)

    def pb(probes: int, spb: int) -> int:
        return (int(probes) + spb - 1) // spb

    b = F * 16                                  # qsub packed subject rows
    b += F * pb(cfg["dh_probes"], 8) * bucket_row       # dh edge probe
    b += F * S * pb(cfg["rh_probes"], 16) * bucket_row  # rh span probe
    if has_delta:
        b += F * pb(DELTA_PROBES, 8) * bucket_row       # dd overlay probe
        b += F * S * pb(DELTA_PROBES, 16) * bucket_row  # dirty-row probe
    b += F * K * 16                             # instruction row lanes
    b += F * 32                                 # srcmat [F, 8] rows
    b += F * 8                                  # e_pack (obj, rel) rows
    b += 2 * F * 16                             # dedupe winner + key rows
    return b


def _mix32(x: jnp.ndarray) -> jnp.ndarray:
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> jnp.uint32(16))
    return x


def _hash_combine(*parts: jnp.ndarray) -> jnp.ndarray:
    shape = jnp.broadcast_shapes(*(jnp.shape(p) for p in parts))
    h = jnp.full(shape, _GOLDEN, dtype=jnp.uint32)
    for p in parts:
        h = _mix32(h ^ p.astype(jnp.uint32))
    return h


def _isolate(x: jnp.ndarray) -> jnp.ndarray:
    """Fence a gather from surrounding fusions: XLA TPU emits a fast
    standalone gather kernel, but a gather fused into a loop fusion
    scalarizes (measured ~6x slower on v5e, tools/microbench2.py)."""
    (x,) = jax.lax.optimization_barrier((x,))
    return x


def _bucket_rows(pack: jnp.ndarray, h1: jnp.ndarray, h2: jnp.ndarray,
                 probes: int, spb: int) -> jnp.ndarray:
    """Gather every table row a probe chain of `probes` slots can touch,
    as BUCKET rows: the device twin of snapshot.probe_slot's bucketized
    sequence. `pack` is [cap, w]; slots j = 0..probes-1 live in buckets
    (h1 + (j//spb)*h2) mod (cap/spb), spb consecutive slots each, so
    PB = ceil(probes/spb) bucket-row gathers of 64 ints (256 B) cover
    the chain. Returns [..., PB*spb, w] slot rows (leading dims = h1's
    shape).

    `spb` MUST be snapshot.slots_per_bucket(n_key_cols) for the probed
    table — each probe helper passes it from the same single source the
    builders key off, so a future table with a new (width, key-count)
    pairing cannot silently probe a different sequence than it was built
    with.

    This is the gather-volume lever (tools/microbench_gather_layout.py:
    a gathered row costs ~the same at any width 32-256 B, and adjacent
    rows do NOT coalesce): one spb-slot bucket row per spb probe slots
    instead of one slot row per probe — the dominant per-step cost
    divides by ~min(probes, spb)."""
    cap, w = pack.shape
    nb = cap // spb
    PB = (probes + spb - 1) // spb
    jb = jnp.arange(PB, dtype=jnp.uint32)
    bidx = ((h1[..., None] + jb * h2[..., None]) & jnp.uint32(nb - 1)).astype(
        jnp.int32
    )  # [..., PB]
    rows = _isolate(pack.reshape(nb, spb * w)[bidx])  # [..., PB, spb*w]
    return rows.reshape(*h1.shape, PB * spb, w)


def _edge_key_probe(tables, prefix, obj, rel, skind, sa, sb, probes: int,
                    key=None):
    """Probe a 5-key edge hash table stored as PACKED rows
    `{prefix}_pack[cap, 8]` = (obj, rel, skind, sa, sb, val, pad, pad),
    fetched as [F, PB, 64] bucket rows (_bucket_rows) — ONE gathered row
    per 8 slots of probe depth, the measured round-5 cost lever.

    Matching compares WHOLE rows against a [F, 8] key matrix (lanes >= 5
    auto-pass; the value rides lane 5 of the same masked reduce), which
    keeps the match+value computation in fused elementwise+reduce form.
    Comparing the full bucket (up to PB*8 slots, possibly beyond the
    exact probe limit) is safe: a slot either holds a different full key
    (never matches) or OUR key placed by the builder inside its own
    chain — extra compared slots can only confirm true membership.
    `key` lets a caller probing two tables with the same key (main +
    delta overlay) build the matrix once. Returns (found[F], value[F])."""
    h1 = _hash_combine(obj, rel, skind, sa, sb)
    h2 = _mix32(h1 ^ _GOLDEN) | jnp.uint32(1)
    rows = _bucket_rows(
        tables[f"{prefix}_pack"], h1, h2, probes, slots_per_bucket(5)
    )  # [F, PB*8, 8]
    if key is None:
        key = edge_probe_key(obj, rel, skind, sa, sb)
    lane = jnp.arange(8, dtype=jnp.int32)
    match = jnp.all((rows == key[:, None, :]) | (lane >= 5), axis=-1)
    found = jnp.any(match, axis=-1)
    # lane-5 extraction rides the same fused reduce (EMPTY = -1 < values)
    val = jnp.max(
        jnp.where(match[:, :, None] & (lane == 5), rows, EMPTY), axis=(1, 2)
    )
    return found, val


def edge_probe_key(obj, rel, skind, sa, sb) -> jnp.ndarray:
    """[F, 8] whole-row key matrix for _edge_key_probe (pad lanes 0)."""
    z = jnp.zeros_like(obj)
    return jnp.stack([obj, rel, skind, sa, sb, z, z, z], axis=-1)


def _multi_pair_key_probe(tables, prefix, obj, rels, probes: int,
                          n_vals: int = 1):
    """Probe a (obj, rel)-keyed packed table `{prefix}_pack[cap, 4]` =
    (obj, rel, val, val2/pad) for MANY relations per task at once.
    `rels` is a [F, S] relation matrix; returns the [F, S] value matrix
    (EMPTY = miss), or with `n_vals=2` a [F, S, 2] matrix carrying BOTH
    value lanes (the rh span table stores (row_start, row_end) so the
    CSR row lookup needs zero extra gathers — both extractions reduce
    over the SAME gathered bucket rows). Each (task, slot) chain rides
    PB = ceil(probes/8) bucket-row gathers ([F, S, PB, 32] via
    _bucket_rows) — the gather count is S*PB rows per task, the
    dominant per-step cost (ablate_step.py)."""
    F, S = rels.shape
    h1 = _hash_combine(obj[:, None], rels)  # [F, S]
    h2 = _mix32(h1 ^ _GOLDEN) | jnp.uint32(1)
    rows = _bucket_rows(
        tables[f"{prefix}_pack"], h1, h2, probes, slots_per_bucket(2)
    )
    # rows: [F, S, PB*8, 4]
    z = jnp.zeros_like(rels)
    key = jnp.stack(
        [jnp.broadcast_to(obj[:, None], rels.shape), rels, z, z], axis=-1
    )  # [F, S, 4]
    lane = jnp.arange(4, dtype=jnp.int32)
    match = jnp.all((rows == key[:, :, None, :]) | (lane >= 2), axis=-1)
    # value extraction through the same masked reduce (EMPTY = -1 floor)
    masked = jnp.where(match[..., None], rows, EMPTY)  # [F, S, PB*8, 4]
    if n_vals == 1:
        return jnp.max(
            jnp.where(lane == 2, masked, EMPTY), axis=(-1, -2)
        )  # [F, S]
    vals = jnp.max(masked, axis=-2)  # [F, S, 4] per-lane winners
    return vals[..., 2 : 2 + n_vals]  # [F, S, n_vals]


def _pair_key_probe(tables, prefix, obj, rel, probes: int):
    """Single-relation probe of a (obj, rel)-keyed table -> value or EMPTY."""
    return _multi_pair_key_probe(tables, prefix, obj, rel[:, None], probes)[:, 0]


def dirty_lookup(tables, obj, rel):
    """Dirty-row bitmask for (obj, rel), 0 when the row is clean."""
    val = _pair_key_probe(tables, "dirty", obj, rel, DELTA_PROBES)
    return jnp.maximum(val, 0)


def pack_edge_table(obj, rel, skind, sa, sb, val) -> np.ndarray:
    """Interleave six edge-table columns into [cap, 8] rows (pad lanes
    zeroed) — the device layout every 5-key probe gathers."""
    import numpy as _np

    cap = obj.shape[0]
    out = _np.zeros((cap, 8), dtype=_np.int32)
    for i, col in enumerate((obj, rel, skind, sa, sb, val)):
        out[:, i] = col
    return out


def pack_pair_table(obj, rel, val) -> np.ndarray:
    """Interleave three (obj, rel)->val columns into [cap, 4] rows."""
    import numpy as _np

    cap = obj.shape[0]
    out = _np.zeros((cap, 4), dtype=_np.int32)
    for i, col in enumerate((obj, rel, val)):
        out[:, i] = col
    return out


def pack_rh_span_table(rh_obj, rh_rel, rh_row, row_ptr) -> np.ndarray:
    """(obj, rel) -> CSR span packed as [cap, 4] rows
    (obj, rel, row_start, row_end): resolving row_ptr at PACK time means
    the kernel's row lookup needs zero extra gathers — the span rides
    the probe's own bucket-row fetch (EMPTY rows pack (-1, -1))."""
    import numpy as _np

    cap = rh_obj.shape[0]
    out = _np.zeros((cap, 4), dtype=_np.int32)
    out[:, 0] = rh_obj
    out[:, 1] = rh_rel
    valid = rh_row != EMPTY
    if row_ptr.shape[0] >= 2:
        rc = _np.clip(rh_row, 0, row_ptr.shape[0] - 2)
        out[:, 2] = _np.where(valid, row_ptr[rc], EMPTY)
        out[:, 3] = _np.where(valid, row_ptr[rc + 1], EMPTY)
    else:
        out[:, 2] = EMPTY
        out[:, 3] = EMPTY
    return out


def pack_instr_table(instr_kind, instr_rel, instr_rel2) -> np.ndarray:
    """Interleave the K-slot instruction columns into [NP, K*4] rows of
    (kind, rel, rel2, pad) lanes — one row-gather per task instead of
    three [F, K] gathers."""
    import numpy as _np

    NP, K = instr_kind.shape
    out = _np.zeros((NP, K, 4), dtype=_np.int32)
    out[..., 0] = instr_kind
    out[..., 1] = instr_rel
    out[..., 2] = instr_rel2
    return out.reshape(NP, K * 4)


def pack_delta_tables(delta: dict) -> dict:
    """The delta overlay's packed device tables (dd_pack + dirty_pack) —
    the ONE place the delta column-to-row layout is defined."""
    return {
        "dd_pack": pack_edge_table(
            delta["dd_obj"], delta["dd_rel"], delta["dd_skind"],
            delta["dd_sa"], delta["dd_sb"], delta["dd_val"],
        ),
        "dirty_pack": pack_pair_table(
            delta["dirty_obj"], delta["dirty_rel"], delta["dirty_val"]
        ),
        # reverse-mirror staleness (engine/reverse_kernel.py); packed
        # here so ONE delta dict serves both traversal directions
        "rd_pack": pack_pair_table(
            delta["rd_obj"], delta["rd_tag"], delta["rd_val"]
        ),
    }


class _State(NamedTuple):
    t_q: jnp.ndarray  # [F] owning query index
    t_ctx: jnp.ndarray  # [F] result accumulator id (0..B-1 = query roots)
    t_obj: jnp.ndarray  # [F] object slot
    t_rel: jnp.ndarray  # [F] relation id
    t_depth: jnp.ndarray  # [F] remaining depth
    n_tasks: jnp.ndarray  # scalar int32
    # ctx_hit[:B] is the per-query root verdict (the old `member`);
    # ctx_hit[B + i*K + k] accumulates island i's leaf-k sub-check
    ctx_hit: jnp.ndarray  # [B + NI*K] bool
    needs_host: jnp.ndarray  # [B] int32 cause code (CAUSE_*; 0 = on device)
    # island instance table (populated only when NI > 0)
    isl_parent: jnp.ndarray  # [max(NI,1)] ctx the island's result ORs into
    isl_pid: jnp.ndarray  # [max(NI,1)] program id (selects the circuit)
    n_isl: jnp.ndarray  # scalar int32
    step: jnp.ndarray  # scalar int32
    stats: jnp.ndarray  # [N_LAUNCH_STATS] launch introspection counters


class Expansion(NamedTuple):
    """Candidate children of one expansion phase (pre-dedupe)."""

    q: jnp.ndarray
    ctx: jnp.ndarray
    obj: jnp.ndarray
    rel: jnp.ndarray
    depth: jnp.ndarray
    valid: jnp.ndarray


def program_lookup(tables, obj, rel, live, *, n_config_rels: int):
    """Shared (ns, has_prog, pid, flags) lookup used by flag_phase and
    expand_phase: the two phases need the identical gathers (objslot_ns,
    prog_flags x2 before this factoring), and the step cost is
    gather-volume bound (tools/ablate_step.py), so recomputing them per
    phase was pure overhead. Pure function of replicated tables."""
    ns = tables["objslot_ns"][jnp.clip(obj, 0, None)]
    has_prog = (rel < n_config_rels) & live
    pid = jnp.where(has_prog, ns * n_config_rels + rel, 0)
    flags = jnp.where(has_prog, tables["prog_flags"][pid], 0)
    return ns, has_prog, pid, flags


def flag_phase(
    tables, obj, rel, live, *, n_config_rels: int, island_is_host: bool = False,
    prog=None,
):
    """Per-task host-replay CAUSE codes (0 = stay on device); pure
    function of replicated tables, so every shard computes the identical
    result (no collective needed). ref: engine.go:219-228
    (relation-not-found), snapshot FLAG_* bits. `island_is_host=True`
    (a kernel compiled with n_island_cap=0) routes AND/NOT programs to
    exact host replay — evaluating them with the pure-union fast path
    would silently corrupt verdicts. The per-task causes here are
    mutually exclusive by construction (a program compiles to exactly one
    of HOST_ONLY / ISLAND / plain; CONFIG_MISSING programs are never
    compiled), so one int code loses nothing vs a bitmask."""
    if prog is None:
        prog = program_lookup(tables, obj, rel, live, n_config_rels=n_config_rels)
    ns, has_prog, pid, flags = prog
    code = jnp.where((flags & FLAG_HOST_ONLY) != 0, CAUSE_REWRITE_CAP, 0)
    code = jnp.where((flags & FLAG_CONFIG_MISSING) != 0, CAUSE_CONFIG_MISSING, code)
    if island_is_host:
        code = jnp.where((flags & FLAG_ISLAND) != 0, CAUSE_ISLAND_HOST, code)
    # a data-only relation (id >= n_config_rels) visited inside a
    # namespace that HAS a relation config is the reference's
    # "relation not found" error (engine.go:219-228): host replay
    rel_nf = (rel >= n_config_rels) & tables["ns_has_config"][ns].astype(bool)
    code = jnp.maximum(code, jnp.where(rel_nf, CAUSE_REL_NOT_FOUND, 0))
    return jnp.where(live, code, 0).astype(jnp.int32)


def probe_phase(
    tables, obj, rel, skind, sa, sb, depth, live, *,
    dh_probes: int, has_delta: bool = True,
):
    """Direct-edge probe; needs depth >= 1 (checkDirect gets restDepth-1).
    A delta-overlay entry for the exact key overrides the compacted table
    (insert adds the edge, tombstone masks a deleted one). `has_delta` is
    static: a clean mirror (the common serving state between writes)
    skips the overlay probe entirely — half the probe gathers."""
    key = edge_probe_key(obj, rel, skind, sa, sb)
    main_hit, main_val = _edge_key_probe(
        tables, "dh", obj, rel, skind, sa, sb, dh_probes, key=key
    )
    # value-liveness: incremental compaction (engine/compact.py) deletes
    # by zeroing the value in place (removing the key would break other
    # keys' probe chains); freshly-built tables store val=1 everywhere,
    # and the value lane rides the same packed-row gather — free
    main_hit = main_hit & (main_val == 1)
    if has_delta:
        in_delta, dval = _edge_key_probe(
            tables, "dd", obj, rel, skind, sa, sb, DELTA_PROBES, key=key
        )
        main_hit = jnp.where(in_delta, dval == 1, main_hit)
    return main_hit & live & (depth >= 1)


def expand_phase(
    tables,
    q,
    ctx,
    obj,
    rel,
    depth,
    live,
    isl_state,
    *,
    K: int,
    rh_probes: int,
    n_config_rels: int,
    wildcard_rel: int,
    n_queries: int,
    n_island_cap: int,
    has_delta: bool = True,
    prog=None,
) -> tuple[Expansion, jnp.ndarray, tuple]:
    """Expand every live task through its CSR row + rewrite instructions.

    Monotone programs: instruction children inherit the task's ctx (any
    hit anywhere resolves the accumulator — pure-union semantics).

    Island programs (FLAG_ISLAND — the rewrite contains AND/NOT): the
    task allocates an island instance; each instruction becomes a LEAF
    sub-check whose children carry a fresh leaf ctx. The island's boolean
    circuit is combined on host after the BFS (engine/islands.py) and the
    result ORs into the task's own ctx — the data-parallel form of the
    reference's synchronous binop.and/checkInverted islands
    (internal/check/binop.go:38-70, rewrites.go:95-159). The task's CSR
    slot (checkExpandSubject) still inherits the task ctx: subject-set
    expansion is an or-branch BESIDE the rewrite, not inside it
    (engine.go:183-207).

    Returns (candidates, per-query host flags, island updates):
    candidates beyond the frontier capacity are truncated and their
    owning queries flagged for host replay; delta-dirty rows and island-
    table overflow flag their queries too.
    """
    F = q.shape[0]
    S = K + 1  # expansion slots per task: CSR row + K instructions
    NI = n_island_cap
    n_edges = tables["e_pack"].shape[0]

    if prog is None:
        prog = program_lookup(tables, obj, rel, live, n_config_rels=n_config_rels)
    ns, has_prog, pid, prog_flags = prog

    # instruction load: ONE [F, K*4] row-gather of the packed
    # (kind, rel, rel2, pad) lanes instead of three [F, K] gathers
    mask_prog = has_prog[:, None]
    ipack = _isolate(tables["instr_pack"][pid]).reshape(F, K, 4)
    ik = jnp.where(mask_prog, ipack[..., 0], INSTR_NONE)  # [F, K]
    ir = jnp.where(mask_prog, ipack[..., 1], 0)
    ir2 = jnp.where(mask_prog, ipack[..., 2], 0)

    # relation per expansion slot: slot 0 = the task's own relation
    # (subject-set row), slots 1..K = the instruction relation
    rels = jnp.concatenate([rel[:, None], ir], axis=1)  # [F, S]

    # row lookup for every (obj, slot-relation): the rh span table
    # stores (row_start, row_end) in its two value lanes, so the CSR
    # span arrives with the probe — no row_ptr gathers at all
    spans = _multi_pair_key_probe(
        tables, "rh", obj, rels, rh_probes, n_vals=2
    )  # [F, S, 2]
    starts = spans[..., 0]
    row_len = jnp.where(starts < 0, 0, spans[..., 1] - starts)

    can_expand = live & (depth >= 1)
    is_comp = (ik == INSTR_COMPUTED) & live[:, None]
    is_ttu = (ik == INSTR_TTU) & (live & (depth >= 1))[:, None]

    counts = jnp.concatenate(
        [
            jnp.where(can_expand, row_len[:, 0], 0)[:, None],
            jnp.where(is_comp, 1, jnp.where(is_ttu, row_len[:, 1:], 0)),
        ],
        axis=1,
    )  # [F, S]

    # per-query host-replay cause codes raised by this phase (int32;
    # scatter-max per flag site — same scatter count as the old booleans)
    overflow_q = jnp.zeros(n_queries, dtype=jnp.int32)

    # delta-dirty rows (stale CSR contents): slot-0 expansion or TTU rows
    if has_delta:
        dirty_vals = _multi_pair_key_probe(
            tables, "dirty", obj, rels, DELTA_PROBES
        )
        row_dirty = (jnp.maximum(dirty_vals, 0) & DIRTY_FOR_CHECK) != 0  # [F, S]
        dirty = (can_expand & row_dirty[:, 0]) | jnp.any(
            is_ttu & row_dirty[:, 1:], axis=1
        )
        overflow_q = overflow_q.at[q].max(
            jnp.where(dirty, CAUSE_DIRTY, 0).astype(jnp.int32)
        )

    # island allocation: one instance per live task whose program has
    # AND/NOT; its instruction slots seed leaf ctxs B + idx*K + (k-1)
    isl_parent, isl_pid, n_isl = isl_state
    if NI > 0:
        is_island = ((prog_flags & FLAG_ISLAND) != 0) & live
        inc = is_island.astype(jnp.int32)
        rank = jnp.cumsum(inc) - inc  # exclusive rank among island tasks
        idx = n_isl + rank
        isl_ok = is_island & (idx < NI)
        # island-table overflow: exact host replay for those queries
        overflow_q = overflow_q.at[q].max(
            jnp.where(is_island & (idx >= NI), CAUSE_ISLAND_OVERFLOW, 0).astype(
                jnp.int32
            )
        )
        dest = jnp.where(isl_ok, idx, NI)
        isl_parent = isl_parent.at[dest].set(ctx, mode="drop")
        isl_pid = isl_pid.at[dest].set(pid, mode="drop")
        n_isl = jnp.minimum(n_isl + inc.sum(), NI)
        # per-(task, slot) child ctx: islands route instruction slots to
        # leaf ctxs; everything else inherits the task ctx
        B = n_queries
        leaf_base = B + idx * K
        slot_ctx = jnp.concatenate(
            [
                ctx[:, None],
                jnp.where(
                    isl_ok[:, None],
                    leaf_base[:, None] + jnp.arange(K, dtype=jnp.int32)[None, :],
                    ctx[:, None],
                ),
            ],
            axis=1,
        )  # [F, S]
        # an overflowed island must not seed leaves under the PARENT ctx
        # (that would mix island semantics into the plain accumulator);
        # its instruction slots are suppressed instead — the query is
        # host-flagged anyway
        suppress = (is_island & ~isl_ok)[:, None]
        counts = jnp.concatenate(
            [
                counts[:, :1],
                jnp.where(suppress, 0, counts[:, 1:]),
            ],
            axis=1,
        )
    else:
        slot_ctx = jnp.broadcast_to(ctx[:, None], (F, S))

    # child relation: slot 0 = edge relation (from e_rel), computed = ir,
    # ttu = ir2; child depth: computed keeps depth, others depth-1
    crel = jnp.concatenate(
        [jnp.zeros((F, 1), jnp.int32), jnp.where(ik == INSTR_COMPUTED, ir, ir2)],
        axis=1,
    )

    flat_counts = counts.reshape(-1)
    offsets = jnp.cumsum(flat_counts) - flat_counts  # exclusive scan
    total = offsets[-1] + flat_counts[-1]

    # queries whose expansions overflow the frontier need host replay
    truncated_seg = (offsets + flat_counts) > F
    seg_q = jnp.repeat(q, S, total_repeat_length=F * S)
    overflow_q = overflow_q.at[seg_q].max(
        jnp.where(
            truncated_seg & (flat_counts > 0), CAUSE_FRONTIER_OVERFLOW, 0
        ).astype(jnp.int32)
    )

    # build candidate children by segmented gather; all per-(task, slot)
    # source columns flatten to [F*S] 1-D arrays (no small-lane layouts).
    # The covering-segment map is backend-picked: on TPU-class backends
    # ONE scatter of segment-start markers + a running max (a
    # searchsorted over [F*S] offsets is ~17 sequential gather rounds of
    # F random rows each, and the step cost there is gather-volume
    # bound); on CPU the scan is the expensive op (lax.cummax measured
    # 0.8 ms per call vs cheap binary-search gathers), so searchsorted
    # stays. Nonempty segments have strictly increasing starts, so both
    # reconstruct the identical mapping.
    j = jnp.arange(F, dtype=jnp.int32)
    if scan_seg_map_backend():
        startpos = jnp.where(flat_counts > 0, offsets, F)  # empty segs drop
        marks = jnp.zeros(F, jnp.int32).at[startpos].max(
            jnp.arange(1, F * S + 1, dtype=jnp.int32), mode="drop"
        )
        seg = jax.lax.cummax(marks) - 1  # -1 before the first segment
    else:
        seg = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32) - 1
    seg = jnp.clip(seg, 0, F * S - 1)
    # within rides srcmat lane 7 (offsets[seg]) — no standalone gather
    in_range = j < jnp.minimum(total, F)

    # ONE [F, 8] row-gather of a stacked per-(task, slot) source matrix
    # replaces seven separate [F]-sized gathers (q[ti], slot_ctx[seg],
    # obj[ti], depth[ti], starts[seg], comp[seg], crel[seg]) — the
    # gather-volume model again: a row costs the same as an element
    srcmat = jnp.stack(
        [
            jnp.broadcast_to(q[:, None], (F, S)),
            slot_ctx,
            jnp.broadcast_to(obj[:, None], (F, S)),
            jnp.broadcast_to(depth[:, None], (F, S)),
            starts,
            jnp.concatenate(
                [jnp.zeros((F, 1), jnp.int32), is_comp.astype(jnp.int32)],
                axis=1,
            ),
            crel,
            offsets.reshape(F, S),  # lane 7: within = j - offsets[seg]
        ],
        axis=-1,
    ).reshape(F * S, 8)
    src = _isolate(srcmat[seg])  # [F, 8]
    src_q = src[:, 0]
    src_ctx = src[:, 1]
    src_obj = src[:, 2]
    src_depth = src[:, 3]
    src_start = src[:, 4]
    src_comp = src[:, 5].astype(bool)
    src_crel = src[:, 6]
    within = j - src[:, 7]
    src_slot0 = (seg % S) == 0

    e = jnp.clip(src_start + within, 0, max(n_edges - 1, 0))
    if n_edges:
        ep = _isolate(tables["e_pack"][e])  # [F, 2] = (obj, rel)
        edge_obj = ep[:, 0]
        edge_rel = ep[:, 1]
    else:
        edge_obj = jnp.zeros(F, jnp.int32)
        edge_rel = jnp.zeros(F, jnp.int32)

    child_obj = jnp.where(src_comp, src_obj, edge_obj)
    child_rel = jnp.where(src_slot0, edge_rel, src_crel)
    child_depth = jnp.where(src_comp, src_depth, src_depth - 1)
    child_valid = in_range & ~(src_slot0 & (edge_rel == wildcard_rel))
    return (
        Expansion(src_q, src_ctx, child_obj, child_rel, child_depth, child_valid),
        overflow_q,
        (isl_parent, isl_pid, n_isl),
    )


def dedupe_phase(
    children: Expansion, F: int, n_queries: int
) -> tuple[
    jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
    jnp.ndarray, jnp.ndarray,
]:
    """Dedupe candidates on (ctx, obj, rel) keeping the deepest instance
    and pack the survivors into the next frontier (ctx implies the query:
    root ctxs ARE query ids, leaf ctxs belong to one island instance).
    Candidates may be longer than F (multi-shard gather); survivors
    beyond F flag their queries for host replay.

    Sort-free: candidates race for a hash bucket (scatter-max of a
    priority encoding depth then candidate index); each candidate then
    reads its bucket's winner back. Losing against the SAME key is a
    duplicate (dropped — the winner carries >= depth); losing against a
    DIFFERENT key (bucket collision) keeps the candidate — dedupe is an
    optimization and duplicates are safe, so collisions only cost slots.
    A sort-based dedupe costs a multi-MB unrolled bitonic network on TPU;
    this is two scatters + a few gathers.

    Returns (t_q, t_obj, t_rel, t_depth, n_new, overflow_q[B]).
    """
    G = children.q.shape[0]
    cap = 1
    while cap < 2 * G:
        cap *= 2
    h = _hash_combine(children.ctx, children.obj, children.rel)
    bucket = (h & jnp.uint32(cap - 1)).astype(jnp.int32)
    bucket = jnp.where(children.valid, bucket, cap)  # invalid -> dropped

    # priority: deeper wins (uint32: depth in the top bits, candidate
    # index below). The bit split is derived from the STATIC candidate
    # count G (= n_shards * F after a multi-shard gather) so winner_idx
    # can never silently truncate — oversized meshes shrink the depth
    # field instead (deep depths tie, acceptable: the step budget caps
    # effective exploration long before such depths anyway).
    idx_bits = max(1, (G - 1).bit_length())
    if idx_bits > 28:
        raise ValueError(
            f"dedupe candidate count {G} needs {idx_bits} index bits; "
            "max 28 (shrink frontier_cap or the shard count)"
        )
    depth_max = (1 << (32 - idx_bits)) - 1
    idx = jnp.arange(G, dtype=jnp.int32)
    prio = (
        jnp.clip(children.depth, 0, depth_max).astype(jnp.uint32)
        << jnp.uint32(idx_bits)
    ) | idx.astype(jnp.uint32)
    winner_prio = (
        jnp.zeros(cap, jnp.uint32).at[bucket].max(prio, mode="drop")
    )
    winner_idx = (
        winner_prio[jnp.clip(bucket, 0, cap - 1)]
        & jnp.uint32((1 << idx_bits) - 1)
    ).astype(jnp.int32)

    won = children.valid & (winner_idx == idx)
    # same-key losers are duplicates; different-key losers survive.
    # ONE packed [G, 4] row-gather of the winners' keys instead of three
    # column gathers: a row-gather costs the same as a one-column gather
    # (gather-volume model, tools/microbench_gather_layout.py), so this
    # is 3 gathered-row sets -> 1
    keys = jnp.stack(
        [children.ctx, children.obj, children.rel,
         jnp.zeros_like(children.ctx)], axis=-1
    )  # [G, 4]
    same_key = jnp.all(keys[winner_idx] == keys, axis=-1)
    keep = children.valid & (won | ~same_key)

    pos = jnp.cumsum(keep) - 1
    n_keep = keep.sum().astype(jnp.int32)
    kept_in_cap = keep & (pos < F)
    # survivors that don't fit in the frontier: their queries go to host
    overflow_q = (
        jnp.zeros(n_queries, dtype=jnp.int32)
        .at[children.q]
        .max(
            jnp.where(
                keep & (pos >= F), CAUSE_FRONTIER_OVERFLOW, 0
            ).astype(jnp.int32),
            mode="drop",
        )
    )
    # non-kept entries park at index F: out-of-bounds scatter drops them
    dest = jnp.where(kept_in_cap, pos, F)
    nt_q = jnp.zeros(F, jnp.int32).at[dest].set(children.q, mode="drop")
    nt_ctx = jnp.zeros(F, jnp.int32).at[dest].set(children.ctx, mode="drop")
    nt_obj = jnp.zeros(F, jnp.int32).at[dest].set(children.obj, mode="drop")
    nt_rel = jnp.zeros(F, jnp.int32).at[dest].set(children.rel, mode="drop")
    nt_depth = jnp.zeros(F, jnp.int32).at[dest].set(children.depth, mode="drop")
    n_new = jnp.minimum(n_keep, F)
    return nt_q, nt_ctx, nt_obj, nt_rel, nt_depth, n_new, overflow_q


def seed_state(
    q_obj, q_rel, q_depth, q_valid, frontier_cap: int, n_island_cap: int = 0,
    K: int = 1,
) -> _State:
    """Initial frontier: one task per valid query (frontier_cap >= B);
    task i starts in root ctx i. NC = B + NI*K ctx accumulators."""
    B = q_obj.shape[0]
    pad = frontier_cap - B
    NC = B + n_island_cap * K
    depth0 = jnp.pad(q_depth.astype(jnp.int32), (0, pad))
    # invalid queries contribute inert tasks (depth -1 ⇒ no probes/expansion)
    depth0 = jnp.where(
        jnp.pad(q_valid, (0, pad), constant_values=False),
        depth0,
        -jnp.ones(frontier_cap, jnp.int32),
    )
    return _State(
        t_q=jnp.pad(jnp.arange(B, dtype=jnp.int32), (0, pad)),
        t_ctx=jnp.pad(jnp.arange(B, dtype=jnp.int32), (0, pad)),
        t_obj=jnp.pad(q_obj.astype(jnp.int32), (0, pad)),
        t_rel=jnp.pad(q_rel.astype(jnp.int32), (0, pad)),
        t_depth=depth0,
        n_tasks=jnp.int32(B),
        ctx_hit=jnp.zeros(NC, dtype=bool),
        needs_host=jnp.zeros(B, dtype=jnp.int32),
        isl_parent=jnp.zeros(max(n_island_cap, 1), jnp.int32),
        isl_pid=jnp.zeros(max(n_island_cap, 1), jnp.int32),
        n_isl=jnp.int32(0),
        step=jnp.int32(0),
        stats=empty_launch_stats(),
    )


def loop_cond(max_steps: int, n_queries: int):
    def cond_fn(st: _State) -> jnp.ndarray:
        return (
            (st.step < max_steps)
            & (st.n_tasks > 0)
            & ~jnp.all(st.ctx_hit[:n_queries] | (st.needs_host > 0))
        )

    return cond_fn


def tpu_class_backend() -> bool:
    """Is the default backend TPU-class (TPU / the axon tunnel)? The
    round-5 cost measurements split two backend-dependent choices off
    this: the loop construct (counted_loop_backend) and expand_phase's
    covering-segment algorithm (scan_seg_map_backend). Each has its own
    predicate so one can be varied (debugging, a future GPU case)
    without silently flipping the other."""
    return jax.default_backend() not in ("cpu",)


def counted_loop_backend() -> bool:
    """Should BFS loops run as counted fori+cond instead of while_loop?

    Measured round 5, BOTH ways:
    - axon-tunneled v5e: every while_loop ITERATION costs ~3.8 ms of
      backend overhead regardless of body (a trivial-body while over
      this state costs the same ~49 ms as the full r04 kernel; a
      max_steps=1 kernel costs the same as max_steps=26) — the counted
      loop removes it and resolved batches pay a cond pass-through.
    - CPU: while_loop iterations are cheap and the loop EXITS EARLY
      (the bench workload resolves in ~4 of 13 budgeted steps); a
      counted loop runs all max_steps bodies-or-conds and measured
      2.2x SLOWER end to end (134.7k -> 62.4k checks/s, this round).

    So the choice keys off the backend at trace time. Semantics are
    identical either way (loop_cond gates both)."""
    return tpu_class_backend()


def bounded_loop(cond_fn, step_fn, init, max_steps: int):
    """Drive step_fn while cond_fn holds, never past max_steps; ONE
    construct-selection site for every BFS loop (check, sharded check,
    both expand kernels) per counted_loop_backend."""
    if not counted_loop_backend():
        return jax.lax.while_loop(cond_fn, step_fn, init)

    def body(i, st):
        return jax.lax.cond(cond_fn(st), step_fn, lambda s: s, st)

    return jax.lax.fori_loop(0, max_steps, body, init)


def scan_seg_map_backend() -> bool:
    """Should expand_phase build its covering-segment map with
    scatter+cummax (TPU-class: binary search = 17 rounds of F random
    gathers) instead of searchsorted (CPU: the scan is the expensive
    op)? See tpu_class_backend."""
    return tpu_class_backend()


def run_bfs_loop(step_fn, init, max_steps: int, n_queries: int):
    """bounded_loop under the check kernels' standard predicate."""
    return bounded_loop(loop_cond(max_steps, n_queries), step_fn, init, max_steps)


def finalize(
    final: _State, max_steps: int, n_queries: int
) -> tuple[
    jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
    jnp.ndarray,
]:
    """Step-budget exhaustion with live tasks means the device did NOT
    finish exploring: those queries must go to the host, not be reported
    NotMember (silent false denials otherwise).

    Returns (ctx_hit, needs_host, isl_parent, isl_pid, n_isl, stats) —
    the engine combines island circuits on host and reads the per-query
    verdict from ctx_hit[:B] (engine/islands.py). needs_host carries the
    CAUSE_* code (nonzero => host replay); stats is the launch's
    introspection counter vector (STAT_* slots)."""
    F = final.t_q.shape[0]
    exhausted = (final.step >= max_steps) & (final.n_tasks > 0)
    live = jnp.arange(F, dtype=jnp.int32) < final.n_tasks
    needs_host = final.needs_host.at[final.t_q].max(
        jnp.where(exhausted & live, CAUSE_STEP_EXHAUSTED, 0).astype(jnp.int32)
    )
    return (
        final.ctx_hit, needs_host, final.isl_parent, final.isl_pid,
        final.n_isl, final.stats,
    )


def _check_kernel_impl(
    tables: dict,
    q_obj: jnp.ndarray,  # [B] seed object slots
    q_rel: jnp.ndarray,  # [B] seed relation ids
    q_depth: jnp.ndarray,  # [B] clamped max depths
    q_skind: jnp.ndarray,  # [B] subject kind (0 plain, 1 set)
    q_sa: jnp.ndarray,  # [B]
    q_sb: jnp.ndarray,  # [B]
    q_valid: jnp.ndarray,  # [B] bool: evaluate on device
    *,
    K: int,
    dh_probes: int,
    rh_probes: int,
    max_steps: int,
    wildcard_rel: int,
    n_config_rels: int,
    frontier_cap: int,
    n_island_cap: int = 0,
    has_delta: bool = True,
) -> tuple[
    jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
    jnp.ndarray,
]:
    """Returns (ctx_hit[B + NI*K], needs_host[B], isl_parent, isl_pid,
    n_isl, stats[N_LAUNCH_STATS]); the per-query verdict is ctx_hit[:B]
    after the host island combine (a no-op for monotone-only configs,
    where n_island_cap=0)."""
    B = q_obj.shape[0]
    F = frontier_cap
    # packed per-query subject key: ONE [F, 4] row-gather per step
    # instead of three [F] gathers (q_skind/q_sa/q_sb share the index q)
    qsub = jnp.stack(
        [q_skind, q_sa, q_sb, jnp.zeros_like(q_skind)], axis=-1
    )  # [B, 4]

    def step_fn(st: _State) -> _State:
        idx = jnp.arange(F, dtype=jnp.int32)
        q = st.t_q
        ctx = st.t_ctx
        root_done = st.ctx_hit[:B] | (st.needs_host > 0)
        # a task dies when its query is resolved (top-level or short-
        # circuit) or its own accumulator already hit (per-ctx
        # short-circuit: an island leaf is an OR accumulation too)
        live = (idx < st.n_tasks) & ~root_done[q] & ~st.ctx_hit[ctx]
        obj, rel, depth = st.t_obj, st.t_rel, st.t_depth

        prog = program_lookup(tables, obj, rel, live, n_config_rels=n_config_rels)
        flagged = flag_phase(
            tables, obj, rel, live,
            n_config_rels=n_config_rels, island_is_host=(n_island_cap == 0),
            prog=prog,
        )
        sub = _isolate(qsub[q])  # [F, 4]
        hit = probe_phase(
            tables, obj, rel, sub[:, 0], sub[:, 1], sub[:, 2], depth, live,
            dh_probes=dh_probes, has_delta=has_delta,
        )
        ctx_hit = st.ctx_hit.at[ctx].max(hit)
        needs_host = st.needs_host.at[q].max(flagged)

        # refresh liveness after accumulator updates (short-circuit)
        live = live & ~(ctx_hit[:B] | (needs_host > 0))[q] & ~ctx_hit[ctx]

        children, overflow_q, isl_state = expand_phase(
            tables, q, ctx, obj, rel, depth, live,
            (st.isl_parent, st.isl_pid, st.n_isl),
            K=K, rh_probes=rh_probes, n_config_rels=n_config_rels,
            wildcard_rel=wildcard_rel, n_queries=B,
            n_island_cap=n_island_cap, has_delta=has_delta, prog=prog,
        )
        needs_host = jnp.maximum(needs_host, overflow_q)

        nt_q, nt_ctx, nt_obj, nt_rel, nt_depth, n_new, overflow2 = dedupe_phase(
            children, F, B
        )
        needs_host = jnp.maximum(needs_host, overflow2)
        # launch introspection: a handful of scalar reductions per step
        # (measured in the committed A/B leg as within-noise); depth >= 0
        # excludes the seed bucket's padding tasks from the live count
        stats = update_launch_stats(
            st.stats,
            st.n_tasks,
            (live & (depth >= 0)).sum(),
            hit.sum(),
            children.valid.sum(),
            n_new,
        )
        return _State(
            nt_q, nt_ctx, nt_obj, nt_rel, nt_depth, n_new,
            ctx_hit, needs_host, *isl_state, st.step + 1, stats,
        )

    init = seed_state(q_obj, q_rel, q_depth, q_valid, F, n_island_cap, K)
    final = run_bfs_loop(step_fn, init, max_steps, B)
    return finalize(final, max_steps, B)


_KERNEL_STATICS = (
    "K", "dh_probes", "rh_probes", "max_steps",
    "wildcard_rel", "n_config_rels", "frontier_cap",
    "n_island_cap", "has_delta",
)

check_kernel = functools.partial(
    jax.jit, static_argnames=_KERNEL_STATICS
)(_check_kernel_impl)


@functools.partial(jax.jit, static_argnames=_KERNEL_STATICS)
def check_kernel_packed(
    tables: dict,
    qpack: jnp.ndarray,
    *,
    K: int,
    dh_probes: int,
    rh_probes: int,
    max_steps: int,
    wildcard_rel: int,
    n_config_rels: int,
    frontier_cap: int,
    n_island_cap: int = 0,
    has_delta: bool = True,
):
    """check_kernel with single-buffer I/O: `qpack` is ONE [7, B] int32
    array (obj, rel, depth, skind, sa, sb, valid) and the result is ONE
    int32 vector [n_isl, ctx_hit(B + NI*K), needs_host(B), isl_parent(NI),
    isl_pid(NI), stats(N_LAUNCH_STATS)]. The launch stats ride the same
    single readback — the flight recorder costs no extra transfer.

    Through the axon TPU tunnel every host<->device buffer transfer pays
    its own round-trip (measured r04: a 4096-batch dispatch cost ~300 ms
    while the r03 per-primitive microbenches showed ~µs compute — seven
    query uploads + five result readbacks of per-call RTT, not kernel
    time). One upload + one readback per batch is the transfer-count
    floor. unpack/concat compile to free reshapes on device."""
    ctx_hit, needs_host, isl_parent, isl_pid, n_isl, stats = _check_kernel_impl(
        tables,
        qpack[0], qpack[1], qpack[2], qpack[3], qpack[4], qpack[5],
        qpack[6].astype(bool),
        K=K, dh_probes=dh_probes, rh_probes=rh_probes, max_steps=max_steps,
        wildcard_rel=wildcard_rel, n_config_rels=n_config_rels,
        frontier_cap=frontier_cap, n_island_cap=n_island_cap,
        has_delta=has_delta,
    )
    return jnp.concatenate([
        n_isl[None].astype(jnp.int32),
        ctx_hit.astype(jnp.int32),
        needs_host.astype(jnp.int32),
        isl_parent.astype(jnp.int32),
        isl_pid.astype(jnp.int32),
        # stats LAST so existing front-anchored slicing (e.g.
        # tools/scale_1e8_shard.py) keeps working unchanged
        stats.astype(jnp.int32),
    ])


def pack_queries(
    q_obj, q_rel, q_depth, q_skind, q_sa, q_sb, q_valid
) -> np.ndarray:
    """Host-side twin of check_kernel_packed's input layout."""
    import numpy as _np

    return _np.stack([
        q_obj, q_rel, q_depth, q_skind, q_sa, q_sb,
        q_valid.astype(_np.int32),
    ]).astype(_np.int32)


def unpack_results(flat: np.ndarray, B: int, n_island_cap: int, K: int):
    """Slice check_kernel_packed's result vector back into
    (ctx_hit, needs_host, isl_parent, isl_pid, n_isl, stats) numpy
    views. `stats` is the launch introspection counter vector
    (STAT_* slots; launch_stats_dict names them)."""
    NI = max(n_island_cap, 1)
    NC = B + n_island_cap * K
    n_isl = int(flat[0])
    ctx_hit = flat[1 : 1 + NC].astype(bool)
    needs_host = flat[1 + NC : 1 + NC + B]
    isl_parent = flat[1 + NC + B : 1 + NC + B + NI]
    isl_pid = flat[1 + NC + B + NI : 1 + NC + B + 2 * NI]
    base = 1 + NC + B + 2 * NI
    stats = flat[base : base + N_LAUNCH_STATS]
    return ctx_hit, needs_host, isl_parent, isl_pid, n_isl, stats


PASSTHROUGH_TABLE_KEYS = (
    "objslot_ns", "ns_has_config", "prog_flags",
)


def pack_raw_tables(raw: dict) -> dict:
    """Interleave the 1-D column arrays into the packed device layout
    (host-side numpy; GraphSnapshot / checkpoint formats stay columnar).
    Everything hot rides packed row layouts: dh/rh bucket tables, the
    (obj, rel) edge pack, and the per-program instruction lanes —
    row_ptr is resolved into the rh span lanes at pack time and never
    uploaded."""
    import numpy as _np

    out = {k: raw[k] for k in PASSTHROUGH_TABLE_KEYS if k in raw}
    out["dh_pack"] = pack_edge_table(
        raw["dh_obj"], raw["dh_rel"], raw["dh_skind"],
        raw["dh_sa"], raw["dh_sb"], raw["dh_val"],
    )
    out["rh_pack"] = pack_rh_span_table(
        raw["rh_obj"], raw["rh_rel"], raw["rh_row"], raw["row_ptr"]
    )
    out["e_pack"] = _np.stack(
        [_np.asarray(raw["e_obj"]), _np.asarray(raw["e_rel"])], axis=-1
    ).astype(_np.int32)
    if "instr_kind" in raw:
        # edge-table-only dicts (per-shard builds: the instruction
        # tables are replicated, packed once by the caller) skip this
        out["instr_pack"] = pack_instr_table(
            raw["instr_kind"], raw["instr_rel"], raw["instr_rel2"]
        )
    if "dd_obj" in raw:
        out.update(pack_delta_tables(raw))
    return out


def snapshot_tables(snapshot: GraphSnapshot, delta: dict | None = None) -> dict:
    """Device-resident table dict for check_kernel (uploads once); the
    delta-overlay tables default to empty (fixed shapes either way)."""
    raw = dict(snapshot.device_arrays())
    raw.update(delta or empty_delta_tables())
    return {k: jnp.asarray(v) for k, v in pack_raw_tables(raw).items()}


def refresh_delta_tables(tables: dict, delta: dict, vocab_arrays: dict) -> dict:
    """New table dict with only the overlay (and the vocab-dependent
    objslot_ns / ns_has_config arrays, which grow with delta vocab) re-
    uploaded; the big compacted tables are reused as-is."""
    out = dict(tables)
    for k, v in vocab_arrays.items():
        out[k] = jnp.asarray(v)
    out.update({k: jnp.asarray(v) for k, v in pack_delta_tables(delta).items()})
    return out


def kernel_static_config(
    snapshot: GraphSnapshot,
    max_depth: int,
    frontier_cap: int,
    n_island_cap: int = 0,
    has_delta: bool = True,
) -> dict:
    """The static kwargs for check_kernel, derived from a snapshot.
    Monotone-only configs force n_island_cap=0 (zero island overhead);
    has_delta=False compiles out the overlay probes for a clean mirror."""
    return dict(
        K=snapshot.K,
        dh_probes=snapshot.dh_probes,
        rh_probes=snapshot.rh_probes,
        # depth decrements bound chain steps; computed hops at constant
        # depth are bounded by the relation count before cycling
        max_steps=int(max_depth + snapshot.n_config_rels + 4),
        wildcard_rel=snapshot.wildcard_rel,
        n_config_rels=max(snapshot.n_config_rels, 1),
        frontier_cap=frontier_cap,
        n_island_cap=n_island_cap if snapshot.island_circuits else 0,
        has_delta=has_delta,
    )
