"""TPU check engine facade.

Owns the device mirror lifecycle and the batched check path:

  - snapshot management: one immutable `_EngineState` per store/config
    version — base GraphSnapshot + vocabulary overlay view + device
    tables + delta overlay. Writes refresh the fixed-shape delta overlay
    (engine/delta.py) in a NEW state object; a full rebuild (compaction)
    happens only on config changes, truncated change logs, or oversized
    deltas. Concurrent batches capture one state atomically and stay
    internally consistent.
  - batching front: single checks ride in padded buckets so the jitted
    kernel compiles once per (bucket, static-config) pair — the
    goroutine-per-branch concurrency of the reference becomes batch-
    dimension parallelism
  - exact-semantics fallback: queries flagged needs_host (AND/NOT rewrite
    islands, config-missing-relation errors, frontier overflow, delta-
    dirty rows) and queries whose namespace/object/relation never occur
    in the graph are re-evaluated by the host ReferenceEngine; proof
    trees always come from the host engine

The public surface mirrors check.Engine (CheckIsMember/CheckRelationTuple,
internal/check/engine.go:54-80) plus batch entry points the RPC layer's
micro-batcher feeds.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from .. import faults as _faults
from ..config import Config
from ..errors import StoreUnavailableError
from ..ketoapi import RelationTuple, Subject, Tree
from ..storage.definitions import DEFAULT_NETWORK, Manager
from .definitions import (
    RESULT_IS_MEMBER,
    RESULT_NOT_MEMBER,
    CheckResult,
    Membership,
    paginate_names,
)
from ..observability import next_launch_id
from .delta import SnapshotView, empty_delta_tables
from .kernel import (
    CAUSE_NAME_UNINDEXED,
    CAUSE_NAMES,
    _KERNEL_STATICS,
    check_kernel,
    estimate_step_gather_bytes,
    kernel_static_config,
    launch_stats_dict,
    snapshot_tables,
)
from .reference import ReferenceEngine
from .snapshot import (
    ArrayMap,
    GraphSnapshot,
    build_snapshot,
    build_snapshot_columnar,
    encode_query_batch,
)

_BUCKETS = (16, 64, 256, 1024, 4096, 16384)

_paginate = paginate_names


def _tables_nbytes(tables) -> int:
    """Device bytes held by a table dict (or the mesh path's
    (sharded, replicated) tuple of dicts) — the snapshot_hbm_bytes gauge."""
    if isinstance(tables, tuple):
        return sum(_tables_nbytes(t) for t in tables)
    if isinstance(tables, dict):
        return sum(int(getattr(v, "nbytes", 0) or 0) for v in tables.values())
    return int(getattr(tables, "nbytes", 0) or 0)


@dataclass
class _EngineState:
    """One consistent device-mirror generation. Immutable except for the
    lazily-built expand fields, which are only written under the engine
    lock and only transition None -> value."""

    snapshot: GraphSnapshot
    view: SnapshotView
    sharded: object  # ShardedSnapshot | None
    tables: object  # dict | (sharded_tables, replicated_tables)
    delta_np: dict
    base_version: int
    covered_version: int
    config_fp: int
    # False for a clean mirror: the kernel compiles out the delta-overlay
    # probes entirely (they're half the probe gathers per step)
    has_delta: bool = False
    # expand-kernel extras (lazy)
    expand_tables: Optional[dict] = None  # device full CSR + dirty tables
    fh_probes: Optional[int] = None
    base_decoder: object = None  # reverse vocab of the base snapshot only
    decoder: object = None  # base_decoder extended with the overlay
    # host mirror of the single-device full CSR (fh_* / f_*): retained so
    # an incremental compaction can PATCH the expand state (affected rows
    # only) instead of dropping it — a lazy full-CSR rebuild costs ~212 s
    # at 1e7 (SCALE_1e7_r04). ~1 GB extra host RAM at 1e7; "garbage"
    # counts tail-rewritten slots for the amortizing rebuild
    expand_np: Optional[dict] = None
    # reverse-reachability subsystem (lazy, engine/reverse_kernel.py):
    # host transposed mirror (patchable by incremental compaction, same
    # retention rationale as expand_np) + its device tables; the
    # list-subjects leg packs its device tables from the expand full CSR
    reverse_np: Optional[dict] = None
    reverse_tables: Optional[dict] = None
    subjects_tables: Optional[dict] = None
    subjects_probes: Optional[int] = None


class TPUCheckEngine:
    def __init__(
        self,
        manager: Manager,
        config: Config,
        nid: str = DEFAULT_NETWORK,
        frontier_cap: int = 1 << 14,
        rewrite_instr_cap: int = 8,
        mesh=None,
        metrics=None,
        tracer=None,
        auto_frontier: bool = True,
        flightrec=None,
    ):
        self.manager = manager
        self.config = config
        self.nid = nid
        # the frontier must hold at least one task per batched query
        self.frontier_cap = max(frontier_cap, _BUCKETS[0])
        # scale the per-launch frontier down for small buckets (step cost
        # is O(frontier), so a 16-query launch must not pay a 16k-task
        # frontier). False pins every launch at `frontier_cap` — for
        # operators who sized it explicitly to keep wide-fanout queries
        # on-device (overflow falls back to exact-but-slow host replay).
        self.auto_frontier = auto_frontier
        self._allowed_buckets = [b for b in _BUCKETS if b <= self.frontier_cap]
        self.rewrite_instr_cap = rewrite_instr_cap
        # multi-chip: a 1-D jax.sharding.Mesh shards the edge tables and
        # runs the SPMD kernel (keto_tpu/parallel); None = single device
        self.mesh = mesh
        self.reference = ReferenceEngine(manager, config)
        self._lock = threading.Lock()
        self._state: Optional[_EngineState] = None
        # mirror-checkpoint persistence runs OUTSIDE self._lock (an
        # O(edges) compressed write must not block check traffic) and is
        # throttled so frequent compaction cycles don't re-write it;
        # throttled snapshots are DEFERRED (timer), never dropped, so the
        # last compaction before an idle period still reaches disk
        self._persist_mu = threading.Lock()
        self._write_mu = threading.Lock()
        self._pending_persist: Optional[GraphSnapshot] = None
        self._persist_timer: Optional[threading.Timer] = None
        self._last_persist = 0.0
        self.persist_min_interval = float(
            config.get("check.mirror_persist_interval", 60.0)
        )
        # push-invalidation (watch hub): a write hook sets an event and a
        # lazy background refresher folds the delta in off the request
        # path — requests then find a state already covering the latest
        # store version instead of paying the refresh inline
        self._refresh_mu = threading.Lock()
        self._refresh_event: Optional[threading.Event] = None
        self._refresh_stopped = False
        self._notify_t = 0.0  # monotonic stamp of the oldest unserved poke
        # monotonic stamp of the last time a state provably covered the
        # store's CURRENT version (every successful _ensure_state):
        # during a store outage `now - _synced_t` is the mirror's
        # staleness AGE, the serve.check.degraded.max_staleness_s
        # ceiling's measurand (0.0 = never synced)
        self._synced_t = 0.0
        # device-path observability (served vs host-fallback checks);
        # `metrics` is an optional observability.Metrics mirror of the same.
        # host_cause splits host_checks by kernel CAUSE_* code (VERDICT r2
        # item 7: "host because AND/NOT overflow" must be distinguishable
        # from "host because error")
        self.stats = {
            "device_checks": 0,
            "host_checks": 0,
            "snapshot_builds": 0,
            "host_cause": {},
        }
        self.metrics = metrics
        # launch flight recorder (observability.FlightRecorder | None):
        # one ring entry per device launch, written at the resolve sync
        # point; launch ids are allocated process-wide either way so logs
        # and typed errors stay correlatable when recording is off
        self.flightrec = flightrec
        # Leopard closure index (engine/closure.py): deep checks answered
        # in one probe step when the index covers them. `closure_enabled`
        # is an attribute (not re-read per batch) so the bench's A/B legs
        # can toggle it per call like the flight recorder
        self.closure_enabled = bool(config.get("closure.enabled", False))
        self._closure = None
        self._closure_mu = threading.Lock()
        if tracer is None:
            from ..observability import _NoopTracer

            tracer = _NoopTracer()
        self.tracer = tracer

    # -- snapshot lifecycle ---------------------------------------------------

    def notify_write(self) -> None:
        """Watch-hub push invalidation: called (via the registry commit
        listener) after every store commit for this nid. Only flips an
        event — the refresher thread does the work, and bursts of writes
        coalesce into one refresh. The per-request staleness check in
        _ensure_state stays as the correctness backstop (out-of-process
        writers, refresh races)."""
        if self._refresh_stopped:
            return
        ev = self._refresh_event
        if ev is None:
            with self._refresh_mu:
                ev = self._refresh_event
                if ev is None:
                    ev = threading.Event()
                    thread = threading.Thread(
                        target=self._push_refresh_loop,
                        args=(ev,),
                        name=f"keto-push-refresh-{self.nid}",
                        daemon=True,
                    )
                    self._refresh_event = ev
                    thread.start()
        if not ev.is_set():
            # stamp the OLDEST unserved poke: refresh_lag_seconds then
            # measures hook -> fold completion, including coalesced bursts
            self._notify_t = time.monotonic()
        ev.set()

    def stop_push_refresh(self) -> None:
        """End the refresher thread. Called when the registry evicts this
        engine from the per-tenant LRU — the thread's bound-method target
        would otherwise pin the evicted engine (and its device mirror) in
        memory forever."""
        self._refresh_stopped = True
        ev = self._refresh_event
        if ev is not None:
            ev.set()

    def _push_refresh_loop(self, ev: threading.Event) -> None:
        while True:
            ev.wait()
            if self._refresh_stopped:
                return
            ev.clear()
            try:
                self._ensure_state()
                self.stats["push_refreshes"] = (
                    self.stats.get("push_refreshes", 0) + 1
                )
                if self.metrics is not None and self._notify_t:
                    self.metrics.refresh_lag_seconds.set(
                        time.monotonic() - self._notify_t
                    )
            except Exception:  # noqa: BLE001 — background refresh must
                # never die; the per-request sync path will surface the
                # error to a caller who can handle it
                import logging

                logging.getLogger("keto_tpu").debug(
                    "push-invalidated mirror refresh failed", exc_info=True
                )

    def _ensure_state(self) -> _EngineState:
        """Returns one consistent engine state.

        A namespace-config change (rewrite programs compile into the
        tables), truncated/oversized change log, or missing change-log
        support compacts — full rebuild; otherwise writes since the base
        snapshot refresh only the fixed-shape delta overlay, so the write
        path never re-uploads the O(edges) tables nor recompiles XLA."""
        from .checkpoint import stable_fingerprint

        store_version = self.manager.version(nid=self.nid)
        namespaces = self.config.namespace_manager().namespaces()
        # process-stable so persisted mirror checkpoints stay comparable
        config_fp = stable_fingerprint([ns.to_dict() for ns in namespaces])
        persist_snap = None
        with self._lock:
            state = self._state
            rebuild = state is None or state.config_fp != config_fp
            if not rebuild and state.covered_version != store_version:
                state = self._delta_refresh(state, store_version)
                rebuild = state is None
            if rebuild:
                with self.tracer.span("engine.snapshot_build") as sp:
                    state, persist_snap = self._rebuild(
                        store_version, config_fp, namespaces
                    )
                    sp.set_attribute("tuples", state.snapshot.n_tuples)
            self._state = state
            self._synced_t = time.monotonic()
        if self.metrics is not None:
            self.metrics.mirror_staleness_age_seconds.set(0.0)
        if persist_snap is not None:
            self._maybe_persist(persist_snap)
        return state

    # -- store-outage degradation (storage/health.py's serve half) ------------

    def degraded_covered_version(self):
        """The store version the CURRENT mirror state covers, with ZERO
        store contact (the store is down when anyone asks) — what a
        degraded response's snaptoken is minted at. None = no state."""
        with self._lock:
            state = self._state
        return None if state is None else state.covered_version

    def mirror_staleness_age_s(self) -> float:
        """Seconds since this engine last confirmed its state covered
        the store's current version — the degraded-serving staleness
        ceiling's measurand. Infinity when never synced."""
        if not self._synced_t:
            return float("inf")
        return time.monotonic() - self._synced_t

    def _degraded_state(self, cause, surface: str) -> _EngineState:
        """The bounded-stale serving gate: the existing mirror state,
        iff the shared degraded-serving rule (storage/health.py
        degraded_gate — one policy for this gate AND snaptoken
        enforcement) permits it: breaker fail-fast, a state exists, age
        under serve.check.degraded.max_staleness_s, and the ambient
        request's snaptoken floor (RequestTrace.min_version, stamped by
        enforce_snaptoken) not above the state's covered version.
        Anything else re-raises the typed 503: a degraded answer is
        byte-identical to an authoritative answer at its snaptoken or
        it is not served at all."""
        from ..observability import current_request_trace
        from ..storage.health import degraded_gate

        with self._lock:
            state = self._state
        age = self.mirror_staleness_age_s()
        if self.metrics is not None and state is not None:
            self.metrics.mirror_staleness_age_seconds.set(
                0.0 if age == float("inf") else age
            )
        rt = current_request_trace()
        degraded_gate(
            cause,
            None if state is None else state.covered_version,
            age,
            self.config.get("serve.check.degraded.max_staleness_s"),
            getattr(rt, "min_version", None) if rt is not None else None,
        )
        self.stats["degraded_serves"] = (
            self.stats.get("degraded_serves", 0) + 1
        )
        if self.metrics is not None:
            self.metrics.store_degraded_serves_total.labels(surface).inc()
        return state

    def _ensure_state_degraded_ok(
        self, surface: str = "check"
    ) -> tuple[_EngineState, bool]:
        """(state, degraded): the normal synced state, or — when the
        store-path breaker is open — the existing mirror state at its
        covered version (the Zanzibar §2.4.1 bounded-staleness degrade:
        availability decays to an older-but-valid snapshot, never to a
        wrong answer or a hung thread)."""
        try:
            return self._ensure_state(), False
        except StoreUnavailableError as e:
            return self._degraded_state(e, surface), True

    def _maybe_persist(self, snap: GraphSnapshot) -> None:
        """Checkpoint the freshly-built mirror without holding the engine
        lock. Writes are throttled to one per persist_min_interval, but a
        throttled snapshot is kept pending and flushed by a timer when
        the window opens — dropping it would leave the cache stale until
        the NEXT rebuild, which may never come before a restart."""
        cache_path = self._mirror_cache_path()
        if cache_path is None:
            return
        with self._persist_mu:
            self._pending_persist = snap
            if self._persist_timer is not None:
                return  # an already-scheduled flush will pick this up
            delay = 0.0
            if self._last_persist:
                delay = max(
                    0.0,
                    self._last_persist
                    + self.persist_min_interval
                    - time.monotonic(),
                )
            # ALWAYS deferred to the timer thread (even delay 0): the
            # O(edges) compressed write never runs on the check/serve
            # thread that happened to trigger the rebuild
            timer = threading.Timer(delay, self._flush_deferred)
            timer.daemon = True
            self._persist_timer = timer
            timer.start()

    def flush_checkpoints(self) -> None:
        """Write any pending mirror checkpoint NOW (synchronously).
        Called by the daemon on graceful shutdown and by tests that
        assert on-disk state; safe to call concurrently."""
        with self._persist_mu:
            timer, self._persist_timer = self._persist_timer, None
        if timer is not None:
            timer.cancel()
        self._flush_deferred()

    def _flush_deferred(self) -> None:
        """Take the pending snapshot under the mutex, write it OUTSIDE —
        _persist_mu protects only the pending/timer fields, never the
        O(edges) compressed write, so a serve thread scheduling the next
        persist can't stall behind an in-flight one. _write_mu serializes
        the actual file writes (rename ordering)."""
        from .checkpoint import save_snapshot

        cache_path = self._mirror_cache_path()
        with self._persist_mu:
            self._persist_timer = None
            snap, self._pending_persist = self._pending_persist, None
        try:
            # ALWAYS pass through _write_mu, even with nothing to write:
            # flush_checkpoints() may race a timer thread that already took
            # the pending snapshot — the empty-handed caller must BARRIER
            # on the in-flight write so "flushed" means "on disk"
            with self._write_mu:
                if cache_path is not None and snap is not None:
                    save_snapshot(snap, cache_path)
            if snap is not None:
                with self._persist_mu:
                    self._last_persist = time.monotonic()
        except OSError as err:  # cache write failure must not block serving
            import logging

            logging.getLogger("keto_tpu").warning(
                "mirror checkpoint write failed: %s", err
            )
            if self.metrics is not None:
                # counted HERE, where the failure is swallowed — the
                # registry-level shutdown catch never sees this path
                self.metrics.checkpoint_write_failures_total.inc()

    def _delta_refresh(
        self, state: _EngineState, store_version: int
    ) -> Optional[_EngineState]:
        """Incremental overlay refresh into a NEW state; None => compact."""
        from .delta import (
            DeltaOverflow,
            build_delta_tables,
            build_vocab_overlay,
        )

        changes_since = getattr(self.manager, "changes_since", None)
        if changes_since is None:
            return None
        ops = changes_since(state.base_version, nid=self.nid)
        if ops is None:
            return None
        try:
            overlay = build_vocab_overlay(state.snapshot, ops)
            view = SnapshotView(state.snapshot, overlay)
            delta = build_delta_tables(view, ops)
        except DeltaOverflow:
            # oversized delta: merge the ops into a new base incrementally
            # (only affected slots/rows) before paying the full O(edges)
            # rebuild — the write-churn cliff fix (engine/compact.py)
            return self._incremental_compact(state, store_version, ops)

        from .kernel import refresh_delta_tables

        vocab_arrays = {
            "objslot_ns": overlay.objslot_ns,
            "ns_has_config": overlay.ns_has_config,
        }
        if self.mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .kernel import pack_delta_tables

            sharded_tables, replicated = state.tables
            replicated = dict(replicated)
            packed = dict(vocab_arrays)
            packed.update(pack_delta_tables(delta))
            for k, v in packed.items():
                replicated[k] = jax.device_put(v, NamedSharding(self.mesh, P()))
            tables = (sharded_tables, replicated)
        else:
            tables = refresh_delta_tables(state.tables, delta, vocab_arrays)

        new_state = _EngineState(
            snapshot=state.snapshot,
            view=view,
            sharded=state.sharded,
            tables=tables,
            delta_np=delta,
            base_version=state.base_version,
            covered_version=store_version,
            config_fp=state.config_fp,
            has_delta=True,
        )
        # carry the base full-CSR + base decoder forward; the dirty tables
        # and overlay extension re-derive from the fresh delta (O(delta))
        if state.expand_tables is not None:
            if self.mesh is not None:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                from .kernel import pack_delta_tables

                sharded_csr, _ = state.expand_tables
                fresh_dirty = {
                    "dirty_pack": jax.device_put(
                        pack_delta_tables(delta)["dirty_pack"],
                        NamedSharding(self.mesh, P()),
                    )
                }
                new_state.expand_tables = (sharded_csr, fresh_dirty)
            else:
                base_csr = {
                    k: v
                    for k, v in state.expand_tables.items()
                    if not k.startswith("dirty_")
                }
                new_state.expand_tables = self._merge_expand_dirty(base_csr, delta)
            new_state.fh_probes = state.fh_probes
            new_state.base_decoder = state.base_decoder
            new_state.decoder = state.base_decoder.extended(overlay)
            new_state.expand_np = state.expand_np
        # reverse-reachability state rides along: the big transposed CSRs
        # follow the BASE snapshot; only the reverse-dirty overlay (rd)
        # re-derives from the fresh delta — queries touching changed
        # subjects/rows host-replay, so no table rebuild on the write path
        if state.reverse_tables is not None:
            new_state.reverse_np = state.reverse_np
            new_state.reverse_tables = self._merge_reverse_dirty(
                state.reverse_tables, delta
            )
        if state.subjects_tables is not None:
            new_state.subjects_tables = self._merge_subjects_dirty(
                state.subjects_tables, delta
            )
            new_state.subjects_probes = state.subjects_probes
        if state.base_decoder is not None and new_state.base_decoder is None:
            new_state.base_decoder = state.base_decoder
            new_state.decoder = state.base_decoder.extended(overlay)
        if self.metrics is not None:
            self.metrics.delta_overlay_ops.set(len(ops))
            self.metrics.compaction_lag_versions.set(
                store_version - state.base_version
            )
        return new_state

    def _incremental_compact(
        self, state: _EngineState, store_version: int, ops
    ) -> Optional[_EngineState]:
        """Delta overflow: fold `ops` into a NEW base snapshot by copying
        + patching only the affected table slots/rows (engine/compact.py)
        instead of the full store re-ingest. None => full rebuild (mesh
        path, too-large op batch, load/garbage/probe gates). The merged
        state drops the expand tables — they lazily rebuild from the
        store on the next expand call; the check path (the write-churn
        hot path) never pays the rebuild."""
        if self.mesh is not None:
            return None  # sharded tables merge per-shard; rebuild for now
        from .checkpoint import stable_fingerprint
        from .compact import merge_ops_into_snapshot

        version = stable_fingerprint([store_version, state.config_fp])
        with self.tracer.span("engine.incremental_compact") as sp:
            merged, enc_u, ins_u = merge_ops_into_snapshot(
                state.snapshot, ops, version, with_encoded=True
            )
            if merged is None:
                return None
            sp.set_attribute("ops", len(ops))
        new_state = _EngineState(
            snapshot=merged,
            view=SnapshotView(merged),
            sharded=None,
            tables=snapshot_tables(merged),
            delta_np=empty_delta_tables(),
            base_version=store_version,
            covered_version=store_version,
            config_fp=state.config_fp,
            has_delta=False,
        )
        # patch the retained expand full-CSR mirror with the same op set
        # (None falls back to the lazy rebuild on next expand)
        expand_np, device_csr, fh_probes = self._patched_expand_state(
            state, enc_u, ins_u
        )
        if expand_np is not None:
            from .expand_kernel import ExpandDecoder

            new_state.expand_np = expand_np
            new_state.fh_probes = fh_probes
            new_state.base_decoder = ExpandDecoder(merged)
            new_state.decoder = new_state.base_decoder.extended(None)
            new_state.expand_tables = self._merge_expand_dirty(
                device_csr, new_state.delta_np
            )
        # patch the retained transposed mirror with the same op set (the
        # reverse twin of the expand patch; None => lazy rebuild). The
        # subjects_tables leg stays None — it re-packs from the freshly
        # patched expand full CSR on the next ListSubjects call (a pack,
        # not a rebuild).
        reverse_np, reverse_tables = self._patched_reverse_state(
            state, enc_u, ins_u, merged
        )
        if reverse_np is not None:
            new_state.reverse_np = reverse_np
            new_state.reverse_tables = self._merge_reverse_dirty(
                reverse_tables, new_state.delta_np
            )
            if new_state.base_decoder is None:
                from .expand_kernel import ExpandDecoder

                new_state.base_decoder = ExpandDecoder(merged)
                new_state.decoder = new_state.base_decoder.extended(None)
        self.stats["incremental_merges"] = (
            self.stats.get("incremental_merges", 0) + 1
        )
        self._set_mirror_gauges(new_state.tables)
        # scheduling only (the O(edges) compressed write runs on the
        # timer thread) — safe under the engine lock
        self._maybe_persist(merged)
        return new_state

    def _set_mirror_gauges(self, tables) -> None:
        """Fresh-base gauges after a rebuild/compaction: empty delta
        overlay, zero compaction lag, current device-table footprint."""
        m = self.metrics
        if m is None:
            return
        m.delta_overlay_ops.set(0)
        m.compaction_lag_versions.set(0)
        m.snapshot_hbm_bytes.set(_tables_nbytes(tables))

    @staticmethod
    def _pack_expand_csr(csr: dict) -> dict:
        """Host full-CSR arrays -> the expand kernel's device table dict."""
        import jax.numpy as jnp

        from .kernel import pack_pair_table

        return {
            "fh_pack": jnp.asarray(pack_pair_table(
                csr["fh_obj"], csr["fh_rel"], csr["fh_row"]
            )),
            "f_row_ptr": jnp.asarray(csr["f_row_ptr"]),
            "f_skind": jnp.asarray(csr["f_skind"]),
            "f_sa": jnp.asarray(csr["f_sa"]),
            "f_sb": jnp.asarray(csr["f_sb"]),
        }

    def _patched_expand_state(self, state: _EngineState, enc_u, ins_u):
        """Patch the retained host full-CSR mirror with the merged ops
        (affected rows only) and return (expand_np, device_csr,
        fh_probes), or (None, None, None) to fall back to the lazy
        rebuild (no mirror retained, or garbage past the amortization
        threshold)."""
        import numpy as np

        from .compact import GARBAGE_FLOOR, GARBAGE_FRACTION, patch_csr

        src = state.expand_np
        if src is None:
            return None, None, None
        per_row: dict = {}
        for (obj, rel, sk, sa, sb), ins in zip(enc_u.tolist(), ins_u.tolist()):
            ch = per_row.setdefault((obj, rel), {"ins": [], "del": set()})
            if ins:
                ch["ins"].append((sk, sa, sb))
                ch["del"].discard((sk, sa, sb))
            else:
                ch["del"].add((sk, sa, sb))
                ch["ins"] = [t for t in ch["ins"] if t != (sk, sa, sb)]
        (fh_obj, fh_rel, fh_row), fh_probes, f_row_ptr, payloads, garbage = (
            patch_csr(
                (src["fh_obj"], src["fh_rel"], src["fh_row"]),
                src["fh_probes"],
                src["f_row_ptr"],
                (src["f_skind"], src["f_sa"], src["f_sb"]),
                per_row,
            )
        )
        total_garbage = src["garbage"] + garbage
        if total_garbage > max(
            GARBAGE_FLOOR, GARBAGE_FRACTION * len(payloads[0])
        ):
            return None, None, None
        expand_np = {
            "fh_obj": fh_obj, "fh_rel": fh_rel, "fh_row": fh_row,
            "fh_probes": fh_probes, "f_row_ptr": f_row_ptr,
            "f_skind": payloads[0], "f_sa": payloads[1], "f_sb": payloads[2],
            "garbage": total_garbage,
        }
        return expand_np, self._pack_expand_csr(expand_np), fh_probes

    def _patched_reverse_state(self, state: _EngineState, enc_u, ins_u, merged):
        """Patch the retained transposed mirror (reverse-edge CSR rows
        keyed by subject slot, seed CSR rows keyed by full subject key)
        with the merged ops — the same patch_csr machinery the forward
        CSRs use. Returns (reverse_np, device tables) or (None, None) for
        the lazy rebuild (no mirror retained, pathological clustering, or
        garbage past the amortization threshold)."""
        from .compact import (
            GARBAGE_FLOOR,
            GARBAGE_FRACTION,
            MergeFallback,
            patch_csr,
        )
        from .reverse_kernel import pack_reverse_tables
        from .snapshot import reverse_subject_tag

        src = state.reverse_np
        if src is None:
            return None, None
        per_rev: dict = {}
        per_seed: dict = {}

        def _apply(per_row, key, pay, ins):
            ch = per_row.setdefault(key, {"ins": [], "del": set()})
            if ins:
                ch["ins"].append(pay)
                ch["del"].discard(pay)
            else:
                ch["del"].add(pay)
                ch["ins"] = [t for t in ch["ins"] if t != pay]

        for (obj, rel, sk, sa, sb), ins in zip(enc_u.tolist(), ins_u.tolist()):
            if sk == 1:
                _apply(per_rev, (sa, 0), (obj, rel, sb), ins)
            tag = int(reverse_subject_tag(sk, sb))
            _apply(per_seed, (sa, tag), (obj, rel), ins)
        try:
            (
                (rvh_obj, rvh_rel, rvh_row), rvh_probes, rv_row_ptr,
                (rv_pobj, rv_prel, rv_sb), g_rev,
            ) = patch_csr(
                (src["rvh_obj"], src["rvh_rel"], src["rvh_row"]),
                src["rvh_probes"],
                src["rv_row_ptr"],
                (src["rv_pobj"], src["rv_prel"], src["rv_sb"]),
                per_rev,
            )
            (
                (rsh_obj, rsh_tag, rsh_row), rsh_probes, rs_row_ptr,
                (rs_obj, rs_rel), g_seed,
            ) = patch_csr(
                (src["rsh_obj"], src["rsh_tag"], src["rsh_row"]),
                src["rsh_probes"],
                src["rs_row_ptr"],
                (src["rs_obj"], src["rs_rel"]),
                per_seed,
            )
        except MergeFallback:
            return None, None
        total_garbage = src["garbage"] + g_rev + g_seed
        if total_garbage > max(
            GARBAGE_FLOOR, GARBAGE_FRACTION * (len(rv_pobj) + len(rs_obj))
        ):
            return None, None
        reverse_np = {
            **src,
            "rvh_obj": rvh_obj, "rvh_rel": rvh_rel, "rvh_row": rvh_row,
            "rvh_probes": rvh_probes, "rv_row_ptr": rv_row_ptr,
            "rv_pobj": rv_pobj, "rv_prel": rv_prel, "rv_sb": rv_sb,
            "rsh_obj": rsh_obj, "rsh_tag": rsh_tag, "rsh_row": rsh_row,
            "rsh_probes": rsh_probes, "rs_row_ptr": rs_row_ptr,
            "rs_obj": rs_obj, "rs_rel": rs_rel,
            "garbage": total_garbage,
        }
        import jax.numpy as jnp

        tables = {
            k: jnp.asarray(v)
            for k, v in pack_reverse_tables(reverse_np, merged).items()
        }
        return reverse_np, tables

    @staticmethod
    def _merge_expand_dirty(base_csr: dict, delta_np: dict) -> dict:
        import jax.numpy as jnp

        from .kernel import pack_delta_tables

        merged = dict(base_csr)
        merged["dirty_pack"] = jnp.asarray(
            pack_delta_tables(delta_np)["dirty_pack"]
        )
        return merged

    @staticmethod
    def _merge_reverse_dirty(base_tables: dict, delta_np: dict) -> dict:
        """Reverse-kernel tables + the delta's reverse-dirty (rd) overlay
        — only the small rd pack re-uploads on a delta refresh."""
        import jax.numpy as jnp

        from .kernel import pack_pair_table

        merged = {k: v for k, v in base_tables.items() if k != "rd_pack"}
        merged["rd_pack"] = jnp.asarray(
            pack_pair_table(
                delta_np["rd_obj"], delta_np["rd_tag"], delta_np["rd_val"]
            )
        )
        return merged

    @staticmethod
    def _merge_subjects_dirty(base_tables: dict, delta_np: dict) -> dict:
        import jax.numpy as jnp

        from .kernel import pack_pair_table

        merged = {k: v for k, v in base_tables.items() if k != "dirty_pack"}
        merged["dirty_pack"] = jnp.asarray(
            pack_pair_table(
                delta_np["dirty_obj"], delta_np["dirty_rel"],
                delta_np["dirty_val"],
            )
        )
        return merged

    def _mirror_cache_path(self) -> Optional[str]:
        d = self.config.get("check.mirror_cache")
        if not d:
            return None
        from .checkpoint import mirror_cache_path

        return mirror_cache_path(d, self.nid)

    def _rebuild(
        self, store_version: int, config_fp, namespaces
    ) -> tuple[_EngineState, Optional[GraphSnapshot]]:
        """Returns (state, snapshot-to-persist). The snapshot is non-None
        only for a fresh build; the caller checkpoints it AFTER releasing
        the engine lock (an O(edges) compressed write must not stall
        check traffic)."""
        from .checkpoint import load_snapshot, stable_fingerprint

        version = stable_fingerprint([store_version, config_fp])
        # warm-restart path: a persisted mirror for exactly this
        # (store version, config) skips the O(edges) host build
        cache_path = self._mirror_cache_path()
        if cache_path is not None and self.mesh is None:
            cached = load_snapshot(cache_path)
            if cached is not None and cached.version == version:
                state = _EngineState(
                    snapshot=cached,
                    view=SnapshotView(cached),
                    sharded=None,
                    tables=snapshot_tables(cached),
                    delta_np=empty_delta_tables(),
                    base_version=store_version,
                    covered_version=store_version,
                    config_fp=config_fp,
                )
                self.stats["snapshot_loads"] = self.stats.get("snapshot_loads", 0) + 1
                self._set_mirror_gauges(state.tables)
                return state, None
            # a checkpoint existed but could not warm this restart:
            # count why (cold-start recovery audit — "stale" is a file
            # for another (store version, config) pair, "corrupt" a
            # torn/truncated/incompatible one). The rebuild below IS the
            # degrade path; answers never depend on the cache.
            import os as _os

            if _os.path.exists(cache_path):
                reason = "stale" if cached is not None else "corrupt"
                self.stats[f"checkpoint_fallback_{reason}"] = (
                    self.stats.get(f"checkpoint_fallback_{reason}", 0) + 1
                )
                if self.metrics is not None:
                    self.metrics.checkpoint_load_fallbacks_total.labels(
                        reason
                    ).inc()
        build_start = time.perf_counter()
        # columnar fast path: stores exposing all_tuple_columns feed the
        # vectorized builder directly — no per-tuple Python objects on
        # the ingest path (the 1e7..1e8-scale requirement)
        columns_fn = getattr(self.manager, "all_tuple_columns", None)
        if columns_fn is not None:
            # vectorized ingest: no per-tuple Python objects on the build
            # path (the 1e7..1e8-scale requirement), single-device AND
            # mesh (the round-2 VERDICT's one structural gap)
            if self.mesh is not None:
                from ..parallel.kernel import place_sharded_tables
                from ..parallel.sharding import build_sharded_snapshot_columnar

                sharded = build_sharded_snapshot_columnar(
                    columns_fn(nid=self.nid), namespaces,
                    n_shards=self.mesh.devices.size,
                    K=self.rewrite_instr_cap, version=version,
                )
                snap = sharded.base
                tables = place_sharded_tables(
                    sharded, self.mesh, axis=self.mesh.axis_names[0],
                    release_columns=True,
                )
            else:
                sharded = None
                snap = build_snapshot_columnar(
                    columns_fn(nid=self.nid), namespaces,
                    K=self.rewrite_instr_cap, version=version,
                )
                tables = snapshot_tables(snap)
            state = _EngineState(
                snapshot=snap,
                view=SnapshotView(snap),
                sharded=sharded,
                tables=tables,
                delta_np=empty_delta_tables(),
                base_version=store_version,
                covered_version=store_version,
                config_fp=config_fp,
            )
            self.stats["snapshot_builds"] += 1
            if self.metrics is not None:
                self.metrics.snapshot_builds_total.inc()
                self.metrics.snapshot_tuples.set(snap.n_tuples)
                self.metrics.snapshot_build_duration.observe(
                    time.perf_counter() - build_start
                )
                self._set_mirror_gauges(tables)
            return state, (snap if self.mesh is None else None)
        # ketolint: allow[lock-blocking-call] reason=the O(edges) mirror rebuild must read the store under the engine lock: the built state is stamped covered_version=store_version, and a write landing mid-read would silently decouple the two; the store never calls back into the engine while holding its own lock (write hooks fire post-commit, outside store locks), so the engine->store lock order cannot invert
        tuples = self.manager.all_relation_tuples(nid=self.nid)
        sharded = None
        if self.mesh is not None:
            from ..parallel import build_sharded_snapshot
            from ..parallel.kernel import place_sharded_tables

            sharded = build_sharded_snapshot(
                tuples,
                namespaces,
                n_shards=self.mesh.devices.size,
                K=self.rewrite_instr_cap,
                version=version,
            )
            snap = sharded.base
            tables = place_sharded_tables(
                sharded, self.mesh, axis=self.mesh.axis_names[0],
                release_columns=True,
            )
        else:
            snap = build_snapshot(
                tuples, namespaces, K=self.rewrite_instr_cap, version=version
            )
            tables = snapshot_tables(snap)
        state = _EngineState(
            snapshot=snap,
            view=SnapshotView(snap),
            sharded=sharded,
            tables=tables,
            delta_np=empty_delta_tables(),
            base_version=store_version,
            covered_version=store_version,
            config_fp=config_fp,
        )
        self.stats["snapshot_builds"] += 1
        if self.metrics is not None:
            self.metrics.snapshot_builds_total.inc()
            self.metrics.snapshot_tuples.set(snap.n_tuples)
            self.metrics.snapshot_build_duration.observe(
                time.perf_counter() - build_start
            )
            self._set_mirror_gauges(tables)
        # mirror checkpoints cover the single-device path only (the
        # sharded build re-derives per-shard tables anyway)
        return state, (snap if self.mesh is None else None)

    def invalidate(self) -> None:
        with self._lock:
            self._state = None

    def mirror_state(self):
        """The current immutable state generation (or None before the
        first build). The anti-entropy scrubber (engine/scrub.py) reads
        it to checksum device tables against `state.snapshot`'s host
        truth — both sides of that comparison live on the SAME state
        object, so the scrub stays consistent even if the engine swaps
        states mid-pass."""
        with self._lock:
            return self._state

    def corrupt_mirror(
        self, table: Optional[str] = None, bit: int = 0
    ) -> Optional[str]:
        """Flip one bit in a device-mirror table in place — the
        `mirror_corrupt` fault's payload (a silent HBM fault stand-in,
        test/smoke only). Returns the corrupted table key, or None when
        no single-device state is built. The host-side snapshot is left
        intact: exactly the divergence the scrubber exists to catch."""
        with self._lock:
            state = self._state
        if state is None or not isinstance(state.tables, dict):
            return None  # mesh path: per-shard tables, not scrubbed
        tables = state.tables
        key = table or max(
            tables,
            key=lambda k: int(getattr(tables[k], "nbytes", 0) or 0),
        )
        import jax.numpy as jnp

        host = np.asarray(tables[key]).copy()
        flat = host.reshape(-1).view(np.uint8)
        if flat.size == 0:
            return None
        flat[bit // 8 % flat.size] ^= np.uint8(1 << (bit % 8))
        with self._lock:
            if self._state is state:  # don't poison a successor state
                tables[key] = jnp.asarray(host)
        self.stats["mirror_corruptions"] = (
            self.stats.get("mirror_corruptions", 0) + 1
        )
        return key

    def hbm_snapshot(self) -> dict:
        """Structured device-memory + staleness accounting for the
        current mirror generation: per-buffer table bytes (forward check
        tables incl. the delta overlay and rewrite programs, plus the
        lazily-built expand/reverse/subjects extras) and how stale the
        mirror is relative to the live store. Served by
        `GET /admin/flightrec` and read by the bench; also refreshes the
        keto_tpu_hbm_table_bytes{buffer} gauges. Zero device contact —
        nbytes is array metadata."""
        with self._lock:
            state = self._state
        if state is None:
            return {"built": False}
        # store read OUTSIDE the engine lock (ketolint lock-discipline)
        store_version = self.manager.version(nid=self.nid)

        def per_key(tables) -> dict:
            if tables is None:
                return {}
            if isinstance(tables, tuple):
                merged: dict = {}
                for part in tables:
                    for k, v in per_key(part).items():
                        merged[k] = merged.get(k, 0) + v
                return merged
            return {
                k: int(getattr(v, "nbytes", 0) or 0)
                for k, v in tables.items()
            }

        check_keys = per_key(state.tables)
        delta_bytes = sum(
            v for k, v in check_keys.items()
            if k in ("dd_pack", "dirty_pack", "rd_pack")
        )
        program_bytes = sum(
            v for k, v in check_keys.items()
            if k in ("instr_pack", "prog_flags", "ns_has_config")
        )
        # closure CSR + its delta overlay broken out as their own buffer
        # families (the Leopard index lives in HBM beside the check
        # tables; capacity planning must see it separately)
        closure_keys = per_key(self.closure_device_tables())
        # device-powering working set (engine/closure_power.py): packed
        # adjacency operands + bit matrices + unpacked step scratch of
        # the LAST device build — transient buffers, reported at their
        # high-water shape so capacity planning sees the build's
        # footprint beside the resident index it produces
        power_keys = {}
        with self._closure_mu:
            if self._closure is not None:
                power_keys = {
                    k: int(v)
                    for k, v in self._closure._power_hbm.items()
                }
        buffers = {
            "check": check_keys,
            "expand": per_key(state.expand_tables),
            "reverse": per_key(state.reverse_tables),
            "subjects": per_key(state.subjects_tables),
            "closure": {
                k: v for k, v in closure_keys.items() if k != "cd_pack"
            },
            "closure_delta": {
                k: v for k, v in closure_keys.items() if k == "cd_pack"
            },
            "closure_power": power_keys,
        }
        totals = {
            name: sum(keys.values()) for name, keys in buffers.items()
        }
        if self.metrics is not None:
            for name, total in totals.items():
                self.metrics.hbm_table_bytes.labels(name).set(total)
        return {
            "built": True,
            "nid": self.nid,
            "n_tuples": state.snapshot.n_tuples,
            "buffers": buffers,
            "totals": totals,
            "delta_overlay_bytes": delta_bytes,
            "rewrite_program_bytes": program_bytes,
            "total_bytes": sum(totals.values()),
            # mirror staleness: how far the served snapshot trails the
            # live store, and how much churn the overlay absorbs
            "base_version": state.base_version,
            "covered_version": state.covered_version,
            "store_version": store_version,
            "staleness_versions": store_version - state.covered_version,
            "compaction_lag_versions": (
                state.covered_version - state.base_version
            ),
            "has_delta": state.has_delta,
        }

    # -- Leopard closure index (engine/closure.py) ----------------------------

    def closure_index(self):
        """The per-engine ClosureIndex (lazily created; a cheap shell
        until the maintenance plane or closure_ensure_built powers it).
        Exists regardless of `closure.enabled` so tests/bench can drive
        it directly; the submit path gates on the enabled flag."""
        with self._closure_mu:
            if self._closure is None:
                from .closure import (
                    DEFAULT_LAG_BUDGET,
                    DEFAULT_MAX_SET_ROWS,
                    ClosureIndex,
                )

                cache_dir = self.config.get("check.mirror_cache")
                cache_path = None
                if cache_dir and self.mesh is None:
                    from .checkpoint import closure_cache_path

                    cache_path = closure_cache_path(cache_dir, self.nid)
                self._closure = ClosureIndex(
                    self.nid,
                    max_set_rows=int(
                        self.config.get(
                            "closure.max_set_rows", DEFAULT_MAX_SET_ROWS
                        )
                    ),
                    lag_budget_versions=int(
                        self.config.get(
                            "closure.lag_budget_versions", DEFAULT_LAG_BUDGET
                        )
                    ),
                    metrics=self.metrics,
                    cache_path=cache_path,
                    powering=str(
                        self.config.get("closure.powering", "host")
                    ),
                    flightrec=self.flightrec,
                )
            return self._closure

    def closure_ensure_built(self) -> bool:
        """Power (or refresh) the closure index for the CURRENT engine
        state and fold in every committed write — the maintenance
        plane's per-pass entry point (keto_tpu/closure), also called by
        tests/bench for a deterministic warm index. Never called on the
        check submit path: powering there would stall a batch."""
        state = self._ensure_state()
        idx = self.closure_index()
        max_depth = self.config.max_read_depth()
        ready = idx.ensure_for(state, self.manager, max_depth)
        # incremental dirty refresh: re-power ONLY the write-perturbed
        # nodes from current content (encoded through the state's
        # overlay view, so post-base vocabulary resolves) — their checks
        # return to the closure without waiting for the next compaction
        idx.refresh_dirty(self.manager, max_depth, view=state.view)
        return ready

    def closure_device_tables(self) -> Optional[dict]:
        """The installed closure device tables (hbm_snapshot's closure
        buffer family), or None before the first build."""
        idx = self._closure
        if idx is None:
            return None
        with idx._mu:
            view = idx._view
        return view.tables if view is not None else None

    def _closure_gate(self, state):
        """(view, fallback_cause): the consistent closure view for one
        submit, or the host-side cause every query in the batch will be
        counted under. A LAGGING index gets one bounded inline catch-up
        attempt (a changes_since read — comparable to the staleness read
        _ensure_state just did) when the lag fits the budget; past the
        budget the batch falls back and the background maintainer owns
        recovery."""
        from .closure import CAUSE_LAG

        idx = self.closure_index()
        view, cause = idx.view_for(state)
        if view is None and cause == CAUSE_LAG:
            lag = idx.lag_versions(state.covered_version)
            try:
                caught = lag <= idx.lag_budget_versions and idx.catch_up(
                    self.manager, state.covered_version
                )
            except StoreUnavailableError:
                # store outage mid-catch-up: the batch falls back to the
                # BFS kernel (cause stays LAG) — a lagging index during
                # an outage degrades latency, never correctness
                caught = False
            if caught:
                view, cause = idx.view_for(state)
        if self.metrics is not None:
            self.metrics.closure_lag_versions.set(
                idx.lag_versions(state.covered_version)
            )
        return view, cause

    def _count_closure_fallback(self, cause: str, n: int) -> None:
        per = self.stats.setdefault("closure_fallback", {})
        per[cause] = per.get(cause, 0) + n
        if self.metrics is not None and n:
            self.metrics.closure_fallback_total.labels(cause).inc(n)

    def _ensure_expand_state(self) -> _EngineState:
        """State with the expand-kernel extras (full-edge CSR + dirty
        tables + decoder) populated. The CSR follows the BASE snapshot;
        writes since then ride the overlay's dirty tables — the expand
        kernel sends queries touching dirty rows to the host, so the CSR
        needs no rebuild on the write path."""
        # store outage: an already-built expand mirror serves degraded
        # at its covered version; a missing one cannot lazily build
        # from a dead store (typed 503 from the read below)
        state = self._ensure_state_degraded_ok("expand")[0]
        if state.expand_tables is not None:
            return state
        import jax.numpy as jnp

        from .expand_kernel import ExpandDecoder, build_full_csr

        with self._lock:
            if state.expand_tables is not None:  # raced with another filler
                return state
            # columnar stores feed the vectorized CSR builders — no
            # per-tuple Python objects on the expand-state build either
            columns_fn = getattr(self.manager, "all_tuple_columns", None)
            if self.mesh is not None:
                # sharded full CSR: same object-slot partition as check
                from ..parallel.expand import place_sharded_expand_tables
                from ..parallel.sharding import (
                    build_sharded_full_csr,
                    build_sharded_full_csr_columnar,
                )

                if columns_fn is not None:
                    stacked, fh_probes = build_sharded_full_csr_columnar(
                        columns_fn(nid=self.nid), state.snapshot,
                        n_shards=self.mesh.devices.size,
                    )
                else:
                    stacked, fh_probes = build_sharded_full_csr(
                        # ketolint: allow[lock-blocking-call] reason=lazy state fill: the full-CSR build must read the store under the engine lock so the derived tables match the state's covered_version exactly; post-commit write hooks fire outside store locks, so the engine->store order cannot invert
                        list(self.manager.all_relation_tuples(nid=self.nid)),
                        state.snapshot,
                        n_shards=self.mesh.devices.size, view=state.view,
                    )
                state.fh_probes = fh_probes
                state.base_decoder = ExpandDecoder(state.snapshot)
                state.decoder = state.base_decoder.extended(state.view.overlay)
                state.expand_tables = place_sharded_expand_tables(
                    stacked, state.delta_np, self.mesh,
                    axis=self.mesh.axis_names[0],
                )
                return state
            if columns_fn is not None:
                from .expand_kernel import build_full_csr_columnar

                csr = build_full_csr_columnar(
                    columns_fn(nid=self.nid), state.snapshot
                )
            else:
                csr = build_full_csr(
                    # ketolint: allow[lock-blocking-call] reason=lazy state fill: the full-CSR build must read the store under the engine lock so the derived tables match the state's covered_version exactly; post-commit write hooks fire outside store locks, so the engine->store order cannot invert
                    list(self.manager.all_relation_tuples(nid=self.nid)),
                    state.snapshot, view=state.view,
                )
            fh_probes = csr.pop("fh_probes")
            device_csr = self._pack_expand_csr(csr)
            state.fh_probes = fh_probes
            state.expand_np = {**csr, "fh_probes": fh_probes, "garbage": 0}
            state.base_decoder = ExpandDecoder(state.snapshot)
            state.decoder = state.base_decoder.extended(state.view.overlay)
            # expand_tables is the readiness signal: set it last
            state.expand_tables = self._merge_expand_dirty(
                device_csr, state.delta_np
            )
            return state

    def _ensure_reverse_state(self) -> _EngineState:
        """State with the transposed mirror (reverse-edge CSR + seed CSR
        + inverted programs) built and on device. Lazy like the expand
        state: the mirror follows the BASE snapshot; writes since then
        ride the delta's reverse-dirty table — affected queries host-
        replay, so the write path never rebuilds it. Under a mesh the
        reverse tables are built unsharded (replicated execution): the
        reverse workload is an analytical read, not the sharded check hot
        path."""
        # store outage: a built transposed mirror serves degraded at its
        # covered version (same contract as the expand state above)
        state = self._ensure_state_degraded_ok("list")[0]
        if state.reverse_tables is not None:
            return state
        import jax.numpy as jnp

        from .expand_kernel import ExpandDecoder
        from .reverse_kernel import (
            build_reverse_state,
            build_reverse_state_columnar,
            pack_reverse_tables,
        )

        namespaces = self.config.namespace_manager().namespaces()
        with self._lock:
            if state.reverse_tables is not None:  # raced another filler
                return state
            columns_fn = getattr(self.manager, "all_tuple_columns", None)
            if columns_fn is not None:
                rnp = build_reverse_state_columnar(
                    columns_fn(nid=self.nid), state.snapshot, namespaces
                )
            else:
                rnp = build_reverse_state(
                    # ketolint: allow[lock-blocking-call] reason=lazy state fill: the full-CSR build must read the store under the engine lock so the derived tables match the state's covered_version exactly; post-commit write hooks fire outside store locks, so the engine->store order cannot invert
                    list(self.manager.all_relation_tuples(nid=self.nid)),
                    state.snapshot, namespaces, view=state.view,
                )
            state.reverse_np = rnp
            if state.base_decoder is None:
                state.base_decoder = ExpandDecoder(state.snapshot)
                state.decoder = state.base_decoder.extended(state.view.overlay)
            tables = {
                k: jnp.asarray(v)
                for k, v in pack_reverse_tables(rnp, state.snapshot).items()
            }
            # reverse_tables is the readiness signal: set it last
            state.reverse_tables = self._merge_reverse_dirty(
                tables, state.delta_np
            )
            return state

    def _ensure_subjects_state(self) -> _EngineState:
        """State with the list-subjects tables (span-packed full-edge CSR
        + instruction lanes) on device. Reuses the expand state's host
        full-CSR mirror when available (single-device path — including
        its incremental-compaction patches); under a mesh it builds its
        own unsharded CSR."""
        state = self._ensure_state_degraded_ok("list")[0]
        if state.subjects_tables is not None:
            return state
        if self.mesh is None:
            state = self._ensure_expand_state()
        import jax.numpy as jnp

        from .expand_kernel import (
            ExpandDecoder,
            build_full_csr,
            build_full_csr_columnar,
        )
        from .reverse_kernel import pack_subjects_tables

        with self._lock:
            if state.subjects_tables is not None:
                return state
            csr = state.expand_np
            if csr is None:
                columns_fn = getattr(self.manager, "all_tuple_columns", None)
                if columns_fn is not None:
                    csr = build_full_csr_columnar(
                        columns_fn(nid=self.nid), state.snapshot
                    )
                else:
                    csr = build_full_csr(
                        # ketolint: allow[lock-blocking-call] reason=lazy state fill: the full-CSR build must read the store under the engine lock so the derived tables match the state's covered_version exactly; post-commit write hooks fire outside store locks, so the engine->store order cannot invert
                        list(self.manager.all_relation_tuples(nid=self.nid)),
                        state.snapshot, view=state.view,
                    )
            state.subjects_probes = int(csr["fh_probes"])
            if state.base_decoder is None:
                state.base_decoder = ExpandDecoder(state.snapshot)
                state.decoder = state.base_decoder.extended(state.view.overlay)
            tables = {
                k: jnp.asarray(v)
                for k, v in pack_subjects_tables(csr, state.snapshot).items()
            }
            state.subjects_tables = self._merge_subjects_dirty(
                tables, state.delta_np
            )
            return state

    # -- reverse reachability (ListObjects / ListSubjects) --------------------

    def _count_reverse(self, leg: str, n_device: int, n_host: int, causes):
        self.stats[f"device_{leg}"] = (
            self.stats.get(f"device_{leg}", 0) + n_device
        )
        self.stats[f"host_{leg}"] = self.stats.get(f"host_{leg}", 0) + n_host
        for cause, cnt in causes.items():
            self.stats["host_cause"][cause] = (
                self.stats["host_cause"].get(cause, 0) + cnt
            )

    def list_objects_batch(
        self,
        queries: Sequence[tuple],
        max_depth: int = 0,
        frontier_cap: int = 4096,
        result_cap: int = 2048,
        pool_cap: int = 0,
    ) -> list[list[str]]:
        """Batched reverse reachability: queries are (namespace,
        relation, subject) triples; each answer is the SORTED list of
        objects in `namespace` the subject reaches via `relation` —
        exactly { obj : Check(ns:obj#rel@subject) is IS_MEMBER }, the
        host oracle's definition (reference.list_objects).

        One device launch per batch (reverse BFS over the transposed
        mirror); queries the kernel cause-flags (AND/NOT programs, dirty
        rows, frontier/result overflow, step exhaustion, error-semantics
        nodes) replay on the exact host oracle. Names the graph+config
        never mention answer [] directly — no edge can seed or match, so
        the enumeration is exactly empty."""
        from ..ketoapi import RelationTuple as _RT
        from ..ketoapi import SubjectSet as _SubjectSet
        from .reverse_kernel import (
            decode_pool_slice,
            list_objects_kernel_packed,
            unpack_list_results,
        )
        from .snapshot import reverse_subject_tag

        n = len(queries)
        if n == 0:
            return []
        state = self._ensure_reverse_state()
        global_max = self.config.max_read_depth()
        depth = max_depth if 0 < max_depth <= global_max else global_max
        rnp = state.reverse_np

        if rnp["host_all"]:
            # a NOT exists somewhere in the config: NOT-members exist
            # precisely where no path exists, which reverse reachability
            # cannot enumerate — exact host oracle for every query
            self._count_reverse(
                "list_objects", 0, n, {"island_host": n}
            )
            return [
                self.reference.list_objects(ns, rel, sub, max_depth, self.nid)
                for ns, rel, sub in queries
            ]

        B = next((b for b in _BUCKETS if b >= n), None)
        if B is None:
            out = []
            step = _BUCKETS[-1]
            for i in range(0, n, step):
                out.extend(
                    self.list_objects_batch(
                        queries[i : i + step], max_depth, frontier_cap,
                        result_cap, pool_cap,
                    )
                )
            return out

        q_sa = np.zeros(B, dtype=np.int32)
        q_tag = np.zeros(B, dtype=np.int32)
        q_ns = np.zeros(B, dtype=np.int32)
        q_rel = np.zeros(B, dtype=np.int32)
        q_valid = np.zeros(B, dtype=bool)
        empty_idx: set[int] = set()
        for i, (ns_name, rel_name, subject) in enumerate(queries):
            ns_id = state.view.ns_id(ns_name)
            rel_id = state.view.rel_id(rel_name)
            proxy = _RT(namespace=ns_name, object="", relation=rel_name)
            if isinstance(subject, _SubjectSet):
                proxy.subject_set = subject
            else:
                proxy.subject_id = subject
            sub = state.view.encode_subject(proxy)
            if ns_id is None or rel_id is None or sub is None:
                empty_idx.add(i)
                continue
            skind, sa, sb = sub
            q_sa[i] = sa
            q_tag[i] = int(reverse_subject_tag(skind, sb))
            q_ns[i] = ns_id
            q_rel[i] = rel_id
            q_valid[i] = True

        qpack = np.stack(
            [
                q_sa, q_tag, q_ns, q_rel,
                np.full(B, depth, dtype=np.int32),
                q_valid.astype(np.int32),
            ]
        ).astype(np.int32)
        launch_id = next_launch_id()
        with self.tracer.span("engine.list_objects_launch", batch=B):
            flat = list_objects_kernel_packed(
                state.reverse_tables,
                qpack,
                rvh_probes=rnp["rvh_probes"],
                rsh_probes=rnp["rsh_probes"],
                RK=rnp["RK"],
                max_steps=int(global_max + state.snapshot.n_config_rels + 4),
                wildcard_rel=state.snapshot.wildcard_rel,
                n_config_rels=max(state.snapshot.n_config_rels, 1),
                frontier_cap=max(frontier_cap, B),
                result_cap=result_cap,
                # default pool sizes for serve-path result sets; callers
                # expecting wide enumerations (the bench) pass pool_cap
                pool_cap=pool_cap or max(8 * B, 4096),
                has_delta=state.has_delta,
            )
        # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
        offs, needs, pool, lstats = unpack_list_results(np.asarray(flat), B)
        self._record_list_launch("list_objects", B, n, lstats, launch_id)
        return self._resolve_reverse(
            "list_objects", queries, empty_idx, q_valid, needs,
            lambda i: sorted(
                state.decoder.slot_to_obj[slot][1]
                for slot in decode_pool_slice(pool, int(offs[i]), int(offs[i + 1]))
            ),
            lambda qr: self.reference.list_objects(
                qr[0], qr[1], qr[2], max_depth, self.nid
            ),
        )

    def list_subjects_batch(
        self,
        queries: Sequence[tuple],
        max_depth: int = 0,
        frontier_cap: int = 4096,
        result_cap: int = 2048,
        pool_cap: int = 0,
    ) -> list[list[str]]:
        """Batched subject enumeration: queries are (namespace, object,
        relation) triples; each answer is the SORTED list of plain
        subject ids with Check(ns:obj#rel@id) IS_MEMBER (the host
        oracle's definition, reference.list_subjects). Forward BFS over
        the full-edge CSR + rewrite instructions with the check kernel's
        exact depth bookkeeping; same cause-coded fallback contract as
        list_objects_batch."""
        from .reverse_kernel import (
            decode_pool_slice,
            list_subjects_kernel_packed,
            unpack_list_results,
        )

        n = len(queries)
        if n == 0:
            return []
        state = self._ensure_subjects_state()
        global_max = self.config.max_read_depth()
        depth = max_depth if 0 < max_depth <= global_max else global_max

        B = next((b for b in _BUCKETS if b >= n), None)
        if B is None:
            out = []
            step = _BUCKETS[-1]
            for i in range(0, n, step):
                out.extend(
                    self.list_subjects_batch(
                        queries[i : i + step], max_depth, frontier_cap,
                        result_cap, pool_cap,
                    )
                )
            return out

        q_obj = np.zeros(B, dtype=np.int32)
        q_rel = np.zeros(B, dtype=np.int32)
        q_valid = np.zeros(B, dtype=bool)
        empty_idx: set[int] = set()
        for i, (ns_name, obj_name, rel_name) in enumerate(queries):
            node = state.view.encode_node(ns_name, obj_name, rel_name)
            if node is None:
                empty_idx.add(i)
                continue
            q_obj[i], q_rel[i] = node
            q_valid[i] = True

        qpack = np.stack(
            [
                q_obj, q_rel,
                np.full(B, depth, dtype=np.int32),
                q_valid.astype(np.int32),
            ]
        ).astype(np.int32)
        launch_id = next_launch_id()
        with self.tracer.span("engine.list_subjects_launch", batch=B):
            flat = list_subjects_kernel_packed(
                state.subjects_tables,
                qpack,
                K=state.snapshot.K,
                fsh_probes=state.subjects_probes,
                max_steps=int(global_max + state.snapshot.n_config_rels + 4),
                wildcard_rel=state.snapshot.wildcard_rel,
                n_config_rels=max(state.snapshot.n_config_rels, 1),
                frontier_cap=max(frontier_cap, B),
                result_cap=result_cap,
                # default pool sizes for serve-path result sets; callers
                # expecting wide enumerations (the bench) pass pool_cap
                pool_cap=pool_cap or max(8 * B, 4096),
                has_delta=state.has_delta,
            )
        # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
        offs, needs, pool, lstats = unpack_list_results(np.asarray(flat), B)
        self._record_list_launch("list_subjects", B, n, lstats, launch_id)
        return self._resolve_reverse(
            "list_subjects", queries, empty_idx, q_valid, needs,
            lambda i: sorted(
                state.decoder.subject_name(sid)
                for sid in decode_pool_slice(pool, int(offs[i]), int(offs[i + 1]))
            ),
            lambda qr: self.reference.list_subjects(
                qr[0], qr[1], qr[2], max_depth, self.nid
            ),
        )

    def _record_list_launch(
        self, kind: str, B: int, n: int, stats, launch_id: int
    ) -> None:
        """Flight-recorder entry for a reverse/expand/filter launch:
        lighter than the check entry (no stage breakdown — these legs
        resolve inline), but the same counter vocabulary. The caller
        allocates `launch_id` BEFORE its kernel dispatch so ids keep
        advancing while recording is disabled and id order tracks
        dispatch order across launch kinds.

        These legs evaluate ON the request thread (no batcher handoff),
        so the executing request's trace rides the ambient contextvar:
        the entry gets the trace id (the `?trace_id=` flightrec filter
        and the exported trace join on it) and the request's trace gets
        the launch id (slow-query lines and request logs then point at
        this entry, exactly like check launches)."""
        from ..observability import current_request_trace

        rt = current_request_trace()
        if rt is not None:
            ids = getattr(rt, "launch_ids", None)
            if ids is not None:
                ids.append(launch_id)
        fr = self.flightrec
        if fr is None or not fr.enabled:
            return
        entry = {
            "launch_id": launch_id,
            "kind": kind,
            "nid": self.nid,
            "bucket": B,
            "n": n,
            "occupancy": round((n / B) if B else 1.0, 4),
        }
        if rt is not None:
            entry["trace_ids"] = [rt.ctx.trace_id]
        if stats is not None:
            entry.update(launch_stats_dict(stats))
        fr.record(entry)

    def _resolve_reverse(
        self, leg, queries, empty_idx, q_valid, needs, decode_fn, host_fn
    ) -> list[list[str]]:
        """Shared result assembly for the two reverse legs: device
        decodes, cause-coded host replays, and stats bookkeeping."""
        results: list[list[str]] = []
        n_host = 0
        causes: dict[str, int] = {}
        for i, qr in enumerate(queries):
            if i in empty_idx:
                # names unknown to graph+config: exactly-empty enumeration
                results.append([])
                continue
            if not q_valid[i] or needs[i]:
                n_host += 1
                cause = (
                    CAUSE_NAMES.get(int(needs[i]), CAUSE_NAME_UNINDEXED)
                    if q_valid[i]
                    else CAUSE_NAME_UNINDEXED
                )
                causes[cause] = causes.get(cause, 0) + 1
                results.append(host_fn(qr))
                continue
            results.append(decode_fn(i))
        self._count_reverse(leg, len(queries) - n_host, n_host, causes)
        return results

    def list_objects(
        self,
        namespace: str,
        relation: str,
        subject,
        max_depth: int = 0,
        page_size: int = 100,
        page_token: str = "",
    ) -> tuple[list[str], str]:
        """Paginated single-query ListObjects: (object names, next page
        token). Tokens are offsets into the sorted enumeration (the batch
        path returns deterministic sorted results, so tokens are stable
        for a fixed snapshot)."""
        objs = self.list_objects_batch([(namespace, relation, subject)], max_depth)[0]
        return _paginate(objs, page_size, page_token)

    def list_subjects(
        self,
        namespace: str,
        obj: str,
        relation: str,
        max_depth: int = 0,
        page_size: int = 100,
        page_token: str = "",
    ) -> tuple[list[str], str]:
        """Paginated single-query ListSubjects: (subject ids, next page
        token)."""
        subs = self.list_subjects_batch([(namespace, obj, relation)], max_depth)[0]
        return _paginate(subs, page_size, page_token)

    # -- bulk ACL filtering (BatchFilter) --------------------------------------

    def _count_filter(
        self, n_closure: int, n_frontier: int, n_host: int, causes
    ) -> None:
        """Per-path resolution bookkeeping for one filter evaluation:
        engine stats + the keto_tpu_filter_objects_total{path} series +
        the shared host_cause split."""
        self.stats["filter_closure"] = (
            self.stats.get("filter_closure", 0) + n_closure
        )
        self.stats["filter_frontier"] = (
            self.stats.get("filter_frontier", 0) + n_frontier
        )
        self.stats["filter_host"] = self.stats.get("filter_host", 0) + n_host
        for cause, cnt in causes.items():
            self.stats["host_cause"][cause] = (
                self.stats["host_cause"].get(cause, 0) + cnt
            )
        if self.metrics is not None:
            for path, n in (
                ("closure", n_closure), ("frontier", n_frontier),
                ("host", n_host),
            ):
                if n:
                    self.metrics.filter_objects_total.labels(path).inc(n)

    @staticmethod
    def _degraded_host_filter_guard(degraded: bool) -> None:
        """Filter has no per-candidate error channel (absence from the
        response means NOT VISIBLE), and the host oracle maps an errored
        candidate to False — during a store outage that would silently
        turn 'unknown' into 'hidden'. A degraded chunk that cannot fully
        resolve on the mirror therefore sheds the typed 503 instead:
        never wrong beats partially answered."""
        if degraded:
            raise StoreUnavailableError(
                "store unavailable and this filter request needs the "
                "exact host oracle for some candidates — retry after "
                "recovery",
                breaker_open=True,
            )

    def _filter_host(self, namespace, relation, subject, objects, max_depth):
        """Exact host-oracle verdicts for a candidate slice (the
        complete checker — the same admission rule the device paths
        reproduce)."""
        return self.reference.filter_objects(
            namespace, relation, subject, objects, max_depth, self.nid
        )

    def filter_batch(
        self,
        namespace: str,
        relation: str,
        subject,
        objects: Sequence[str],
        max_depth: int = 0,
        frontier_cap: int = 4096,
        deadline=None,
        chunk_size: int = 0,
    ) -> list[bool]:
        """Bulk ACL filter: verdicts[i] is True iff
        Check(namespace:objects[i]#relation@subject) is IS_MEMBER — the
        search-result-filtering workload (Zanzibar's dominant production
        query shape) priced as ONE device ride instead of N.

        Device formulation (the shared-subject exploit):
          1. closure fast path — every candidate covered by the Leopard
             index resolves with a single batched membership gather over
             the packed-bucket subject-set tables (`req <= depth` gating
             exactly as closure_kernel.py); no per-object BFS at all.
          2. shared-frontier fallback (engine/filter_kernel.py) — the
             subject's reverse-reachable set expands ONCE over the
             transposed mirror and intersects against the whole leftover
             candidate column; a clean completed walk answers positives
             AND definitive negatives.
          3. cause-coded host fallback — AND/NOT islands (the reverse
             kernel's POISON discipline), dirty rows, overflow, unknown
             vocabulary, or a NOT-bearing config replay on the exact
             host oracle (reference.filter_objects).

        `deadline` (observability.Deadline | None) is checked at every
        chunk boundary — a 10k-object request respects its budget by
        failing fast with the typed 504 instead of finishing device work
        whose client is gone. `chunk_size` 0 reads filter.chunk_size."""
        from ..errors import DeadlineExceededError

        n = len(objects)
        if n == 0:
            return []
        self.stats["filter_requests"] = (
            self.stats.get("filter_requests", 0) + 1
        )
        if self.metrics is not None:
            self.metrics.filter_requests_total.inc()
            self.metrics.filter_request_objects.observe(n)
        chunk = int(
            chunk_size or self.config.get("filter.chunk_size", 4096)
        )
        chunk = max(1, min(chunk, _BUCKETS[-1]))
        out: list[bool] = []
        for i in range(0, n, chunk):
            if deadline is not None and deadline.expired():
                if self.metrics is not None:
                    self.metrics.deadline_exceeded_total.labels(
                        "filter_chunk"
                    ).inc()
                raise DeadlineExceededError(
                    "filter deadline expired mid-evaluation "
                    f"({i}/{n} candidates answered)"
                )
            out.extend(
                self._filter_chunk(
                    namespace, relation, subject, list(objects[i : i + chunk]),
                    max_depth, frontier_cap,
                )
            )
        return out

    def filter_objects(
        self,
        namespace: str,
        relation: str,
        subject,
        objects: Sequence[str],
        max_depth: int = 0,
        deadline=None,
    ) -> list[str]:
        """The transport-facing subset form: the candidates the subject
        CAN see, in input order (duplicates preserved — each occurrence
        answers independently, like N checks would)."""
        verdicts = self.filter_batch(
            namespace, relation, subject, objects, max_depth,
            deadline=deadline,
        )
        return [o for o, ok in zip(objects, verdicts) if ok]

    def _filter_chunk(
        self, namespace, relation, subject, objects, max_depth, frontier_cap
    ) -> list[bool]:
        """One bounded evaluation: closure probe, shared-frontier walk,
        host replay — in that order, each consuming what the previous
        stage could not resolve."""
        from ..ketoapi import RelationTuple as _RT
        from ..ketoapi import SubjectSet as _SubjectSet
        from .closure_kernel import CL_CAUSE_NAMES
        from .filter_kernel import (
            filter_kernel_packed,
            pack_filter_query,
            unpack_filter_results,
        )
        from .snapshot import (
            FLAG_HOST_ONLY as _F_HOST,
            FLAG_ISLAND as _F_ISL,
            reverse_subject_tag,
        )

        n = len(objects)
        # store outage: the chunk serves from the mirror at its covered
        # version (closure probe + shared-frontier walk need no store);
        # candidates that fall to the host replay get the typed
        # per-item error from the dead store via reference.filter_objects
        state, degraded = self._ensure_state_degraded_ok("filter")
        global_max = self.config.max_read_depth()
        depth = max_depth if 0 < max_depth <= global_max else global_max

        # monotone-only configs (no AND/NOT islands, no host-only
        # rewrites anywhere): membership needs an actual edge path, and
        # the reference has no trivial self-membership, so a subject or
        # candidate whose name never encodes is DEFINITIVELY invisible —
        # False with zero device or host work (errors the candidate's
        # region could raise map to False on the filter surface anyway).
        # Any island/host-only program disables the shortcut: a NOT can
        # make unknown names members, so they host-replay instead.
        monotone_vocab = not bool(
            np.any(state.snapshot.prog_flags & (_F_HOST | _F_ISL))
        )

        # -- shared-query encoding (one subject, one relation) ----------------
        ns_id = state.view.ns_id(namespace)
        rel_id = state.view.rel_id(relation)
        proxy = _RT(namespace=namespace, object="", relation=relation)
        if isinstance(subject, _SubjectSet):
            proxy.subject_set = subject
        else:
            proxy.subject_id = subject
        sub = state.view.encode_subject(proxy)
        if ns_id is not None and rel_id is not None and sub is None \
                and monotone_vocab:
            # known target node vocabulary, unknown subject, monotone
            # config: no edge can mention the subject — every candidate
            # is a definitive NOT_MEMBER
            self._count_filter(0, 0, 0, {})
            self.stats["filter_vocab"] = (
                self.stats.get("filter_vocab", 0) + n
            )
            if self.metrics is not None:
                self.metrics.filter_objects_total.labels("vocab").inc(n)
            return [False] * n
        if ns_id is None or rel_id is None or sub is None:
            # names unknown to graph+config under a non-monotone (or
            # unknown-relation) config: error semantics and NOT rewrites
            # may still apply per candidate — exact host eval
            self._degraded_host_filter_guard(degraded)
            verdicts = self._filter_host(
                namespace, relation, subject, objects, max_depth
            )
            self._count_filter(0, 0, n, {CAUSE_NAME_UNINDEXED: n})
            return verdicts
        # ketolint: allow[host-sync] reason=encode_subject returns host-side python/numpy scalars (vocabulary lookups never touch the device), so these int() coercions cannot sync
        skind, sa, sb = (int(x) for x in sub)

        # -- candidate encoding: one composed-key binary search ---------------
        from .snapshot import encode_object_column

        # ketolint: allow[host-sync] reason=ns_id is a host-side vocabulary lookup result (python int / numpy scalar), never a device value — no sync
        c_obj, c_valid = encode_object_column(state.view, int(ns_id), objects)

        # resolved/value masks instead of a per-candidate Python loop:
        # at 10k candidates the bookkeeping must be numpy-vectorized or
        # the host loop dominates the device work it orchestrates
        resolved = np.zeros(n, dtype=bool)
        value = np.zeros(n, dtype=bool)
        causes: dict[str, int] = {}
        n_closure = 0
        n_vocab = 0
        if monotone_vocab and not c_valid.all():
            # candidate names unknown to graph+config: no edge can seed
            # or match them — definitive NOT_MEMBER (the common "most of
            # these documents have no ACLs at all" case answers free)
            unknown = ~c_valid
            resolved |= unknown  # value stays False
            n_vocab = int(unknown.sum())
            self.stats["filter_vocab"] = (
                self.stats.get("filter_vocab", 0) + n_vocab
            )
            if self.metrics is not None:
                self.metrics.filter_objects_total.labels("vocab").inc(n_vocab)

        # -- 1. closure fast path: one batched subject-set gather -------------
        if self.closure_enabled:
            cl_view, cl_cause = self._closure_gate(state)
            if cl_view is not None:
                from .closure_kernel import (
                    closure_kernel_packed,
                    unpack_closure_results,
                )
                from .kernel import pack_queries

                B = next((b for b in _BUCKETS if b >= n), _BUCKETS[-1])
                q_obj = np.zeros(B, dtype=np.int32)
                q_obj[:n] = c_obj[:n]
                q_valid = np.zeros(B, dtype=bool)
                q_valid[:n] = c_valid[:n]
                launch_id = next_launch_id()
                with self.tracer.span("engine.filter_closure", batch=B):
                    flat = closure_kernel_packed(
                        cl_view.tables,
                        pack_queries(
                            q_obj,
                            np.full(B, rel_id, dtype=np.int32),
                            np.full(B, depth, dtype=np.int32),
                            np.full(B, skind, dtype=np.int32),
                            np.full(B, sa, dtype=np.int32),
                            np.full(B, sb, dtype=np.int32),
                            q_valid,
                        ),
                        cc_probes=cl_view.cc_probes,
                        ch_probes=cl_view.ch_probes,
                        has_dirty=cl_view.has_dirty,
                    )
                member, ccause, cstats = unpack_closure_results(
                    # ketolint: allow[host-sync] reason=this IS the closure probe's designated sync point: one packed readback carries verdicts, causes, and the launch stats vector — the shared single-transfer resolve contract
                    np.asarray(flat), B,
                )
                self._record_list_launch(
                    "filter_closure", B, n, cstats, launch_id
                )
                ok = c_valid & (ccause[:n] == 0)
                value |= member[:n] & ok
                resolved |= ok
                n_closure = int(ok.sum())
                declined = c_valid & ~ok
                if declined.any():
                    codes, cnts = np.unique(
                        ccause[:n][declined], return_counts=True
                    )
                    for code, cnt in zip(codes.tolist(), cnts.tolist()):
                        self._count_closure_fallback(
                            # ketolint: allow[host-sync] reason=code is a host python int from np.unique(...).tolist() over the already-synced readback — no device contact
                            CL_CAUSE_NAMES.get(int(code), "uncovered"),
                            # ketolint: allow[host-sync] reason=cnt is a host python int from the same tolist() — no device contact
                            int(cnt),
                        )
            elif cl_cause is not None:
                self._count_closure_fallback(cl_cause, n)

        vp = np.flatnonzero(c_valid & ~resolved)
        n_frontier = 0

        # -- 2. shared-frontier walk over the leftover column -----------------
        if len(vp):
            rstate = self._ensure_reverse_state()
            rnp = rstate.reverse_np
            if rstate.snapshot is not state.snapshot:
                # a compaction swapped the base snapshot between the
                # encode and the reverse build: candidate slots no
                # longer address these tables — exact host replay for
                # the leftovers (rare; the next call re-encodes)
                causes[CAUSE_NAME_UNINDEXED] = (
                    causes.get(CAUSE_NAME_UNINDEXED, 0) + len(vp)
                )
            elif rnp["host_all"]:
                # a NOT exists somewhere in the config: NOT-members
                # exist precisely where no path exists, which the
                # reachability walk cannot observe — exact host oracle
                causes["island_host"] = (
                    causes.get("island_host", 0) + len(vp)
                )
            else:
                uniq = np.unique(c_obj[vp])
                C = next(
                    (b for b in _BUCKETS if b >= len(uniq)), _BUCKETS[-1]
                )
                qc = pack_filter_query(
                    sa, int(reverse_subject_tag(skind, sb)), rel_id, depth,
                    uniq, C,
                )
                launch_id = next_launch_id()
                with self.tracer.span("engine.filter_launch", batch=C):
                    flat = filter_kernel_packed(
                        rstate.reverse_tables,
                        qc,
                        rvh_probes=rnp["rvh_probes"],
                        rsh_probes=rnp["rsh_probes"],
                        RK=rnp["RK"],
                        max_steps=int(
                            global_max + state.snapshot.n_config_rels + 4
                        ),
                        wildcard_rel=state.snapshot.wildcard_rel,
                        n_config_rels=max(state.snapshot.n_config_rels, 1),
                        frontier_cap=max(frontier_cap, 1024),
                        has_delta=state.has_delta,
                    )
                hit, wcause, fstats = unpack_filter_results(
                    # ketolint: allow[host-sync] reason=this IS the filter walk's designated sync point: resolve is the synchronize phase of the split-phase contract, and the single-buffer design makes this readback the ONE device->host transfer for the whole candidate column
                    np.asarray(flat), C,
                )
                self._record_list_launch(
                    "filter", C, len(vp), fstats, launch_id
                )
                if wcause == 0:
                    # clean completed walk: hits are members, unmarked
                    # candidates are definitive NOT_MEMBER
                    pos = np.searchsorted(uniq, c_obj[vp])
                    value[vp] = hit[pos]
                    resolved[vp] = True
                    n_frontier = len(vp)
                else:
                    name = CAUSE_NAMES.get(wcause, CAUSE_NAME_UNINDEXED)
                    causes[name] = causes.get(name, 0) + len(vp)

        # -- 3. exact host replay for everything still unresolved -------------
        host_idx = np.flatnonzero(~resolved)
        if len(host_idx):
            unindexed = len(host_idx) - sum(causes.values())
            if unindexed > 0:
                # candidates whose vocabulary never encoded (under a
                # non-monotone config, where unknown is not a verdict)
                causes[CAUSE_NAME_UNINDEXED] = (
                    causes.get(CAUSE_NAME_UNINDEXED, 0) + unindexed
                )
            self._degraded_host_filter_guard(degraded)
            host_verdicts = self._filter_host(
                namespace, relation, subject,
                # ketolint: allow[host-sync] reason=host_idx is host numpy (np.flatnonzero over a host mask) — these int() coercions never touch a device value
                [objects[int(i)] for i in host_idx], max_depth,
            )
            value[host_idx] = host_verdicts
            resolved[host_idx] = True
        self._count_filter(n_closure, n_frontier, len(host_idx), causes)
        return value.tolist()

    # -- check API ------------------------------------------------------------

    def check_is_member(
        self, r: RelationTuple, max_depth: int = 0
    ) -> bool:
        res = self.check_batch([r], max_depth)[0]
        if res.error is not None:
            raise res.error
        return res.membership == Membership.IS_MEMBER

    def check_relation_tuple(
        self, r: RelationTuple, max_depth: int = 0
    ) -> CheckResult:
        """Single check; proof trees come from the host engine, so this
        delegates entirely (the RPC check path wants only `allowed` and
        uses check_batch)."""
        return self.reference.check_relation_tuple(r, max_depth, self.nid)

    def expand(self, subject: Subject, max_depth: int = 0) -> Optional[Tree]:
        res = self.expand_batch([subject], max_depth)
        return res[0]

    def expand_batch(
        self,
        subjects: Sequence[Subject],
        max_depth: int = 0,
        frontier_cap: int = 1024,
        edge_cap: int = 4096,
        pool_cap: int = 0,
    ) -> list:
        """Batched expand: device BFS subgraph gather + exact host DFS
        assembly (engine/expand_kernel.py); SubjectIDs and overflowing /
        unknown-vocabulary / delta-dirty queries fall back to the host."""
        from ..ketoapi import SubjectSet as _SubjectSet
        from .expand_kernel import assemble_tree, decode_edge_buffer, expand_kernel

        n = len(subjects)
        if n == 0:
            return []
        state = self._ensure_expand_state()
        global_max = self.config.max_read_depth()
        depth = max_depth if 0 < max_depth <= global_max else global_max

        B = next((b for b in _BUCKETS if b >= n), None)
        if B is None:
            out = []
            step = _BUCKETS[-1]
            for i in range(0, n, step):
                out.extend(
                    self.expand_batch(subjects[i : i + step], max_depth,
                                      frontier_cap, edge_cap, pool_cap)
                )
            return out

        host_idx: set[int] = set()
        if isinstance(state.snapshot.obj_slots, ArrayMap):
            # big-vocab snapshots: vectorized node encoding (scalar
            # ArrayMap lookups cost ~1 ms each at 1e7 vocab)
            from .snapshot import encode_node_batch

            triples = []
            for i, sub in enumerate(subjects):
                if isinstance(sub, _SubjectSet):
                    triples.append((sub.namespace, sub.object, sub.relation))
                else:
                    triples.append(None)
                    host_idx.add(i)
            q_obj, q_rel, q_valid = encode_node_batch(state.view, triples, B)
            for i in np.flatnonzero(~q_valid[: len(subjects)]):
                # unknown to graph+config: no tuples can match => nil
                # tree, but keep exact host semantics for the verdict
                # ketolint: allow[host-sync] reason=host numpy value (np.flatnonzero over a host-side validity mask), not a device array — no sync occurs
                host_idx.add(int(i))
        else:
            q_obj = np.zeros(B, dtype=np.int32)
            q_rel = np.zeros(B, dtype=np.int32)
            q_valid = np.zeros(B, dtype=bool)
            for i, sub in enumerate(subjects):
                if not isinstance(sub, _SubjectSet):
                    host_idx.add(i)
                    continue
                node = state.view.encode_node(
                    sub.namespace, sub.object, sub.relation
                )
                if node is None:
                    # unknown to graph+config: no tuples can match =>
                    # nil tree, but keep exact host semantics
                    host_idx.add(i)
                    continue
                q_obj[i], q_rel[i] = node
                q_valid[i] = True

        launch_id = next_launch_id()
        if self.mesh is not None:
            from ..parallel.expand import sharded_expand_kernel

            sharded_csr, replicated_dirty = state.expand_tables
            eb = sharded_expand_kernel(
                self.mesh, sharded_csr, replicated_dirty,
                q_obj, q_rel,
                np.full(B, depth, dtype=np.int32),
                q_valid,
                fh_probes=state.fh_probes,
                max_steps=global_max + 2,
                frontier_cap=max(frontier_cap, B),
                edge_cap=edge_cap,
                axis=self.mesh.axis_names[0],
            )
        else:
            from .expand_kernel import (
                expand_kernel_packed,
                unpack_expand_results,
            )

            # single-buffer I/O + device-side compaction: the raw edge
            # buffers are [B*edge_cap] (~99% padding at real tree sizes);
            # through the axon tunnel that readback, not kernel compute,
            # was the 2.9 s/batch in the r04 first capture. Pool overflow
            # flags needs_host — exact host replay, same contract as
            # edge_cap overflow. Callers expecting wide trees (the scale
            # bench's RBAC fixtures) pass pool_cap explicitly; the
            # default sizes for serve-path trees (~10 nodes avg).
            pool_cap = pool_cap or max(32 * B, 4096)
            qpack = np.stack([
                q_obj, q_rel, np.full(B, depth, dtype=np.int32),
                q_valid.astype(np.int32),
            ]).astype(np.int32)
            flat = expand_kernel_packed(
                state.expand_tables,
                qpack,
                fh_probes=state.fh_probes,
                # static step budget keyed to the GLOBAL depth cap, not the
                # per-call depth (avoids one recompile per requested depth);
                # the loop exits early once the frontier drains
                max_steps=global_max + 2,
                frontier_cap=max(frontier_cap, B),
                edge_cap=edge_cap,
                pool_cap=pool_cap,
            )
            offs, root_has_children, needs_host, pool_cols, estats = (
                # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
                unpack_expand_results(np.asarray(flat), B, pool_cap)
            )
            self._record_list_launch("expand", B, n, estats, launch_id)
            eb = None
        if eb is not None:
            eb_pobj, eb_prel, eb_skind, eb_sa, eb_sb = (
                # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
                np.asarray(x) for x in eb[:5]
            )
            # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
            eb_count = np.asarray(eb[5])
            # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
            root_has_children = np.asarray(eb[6])
            # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
            needs_host = np.asarray(eb[7])
            if self.flightrec is not None and self.flightrec.enabled:
                # gated so a DISABLED recorder costs zero extra
                # transfers on the mesh path (the eager np.asarray
                # would otherwise run before record()'s enabled check)
                self._record_list_launch(
                    # ketolint: allow[host-sync] reason=part of the same designated resolve sync point: the sharded expand's replicated stats vector reads back with the batch results, not as an extra round-trip
                    "expand", B, n, np.asarray(eb[8]), launch_id
                )
            offs = None
            pool_cols = None

        results = []
        n_host_exp = 0
        for i, sub in enumerate(subjects):
            if i in host_idx or not q_valid[i] or needs_host[i]:
                n_host_exp += 1
                results.append(self.reference.expand(sub, max_depth, self.nid))
                continue
            if offs is not None:
                adjacency = decode_edge_buffer(
                    *pool_cols, int(offs[i + 1] - offs[i]), int(offs[i]),
                )
            else:
                adjacency = decode_edge_buffer(
                    eb_pobj, eb_prel, eb_skind, eb_sa, eb_sb,
                    int(eb_count[i]), i * edge_cap,
                )
            results.append(
                assemble_tree(
                    sub, int(q_obj[i]), int(q_rel[i]), depth,
                    adjacency, bool(root_has_children[i]), state.decoder,
                )
            )
        self.stats["device_expands"] = (
            self.stats.get("device_expands", 0) + n - n_host_exp
        )
        self.stats["host_expands"] = self.stats.get("host_expands", 0) + n_host_exp
        return results

    def check_batch(
        self, tuples: Sequence[RelationTuple], max_depth: int = 0
    ) -> list[CheckResult]:
        """Batched membership checks (no proof trees)."""
        return self.check_batch_resolve(self.check_batch_submit(tuples, max_depth))

    def check_batch_host(
        self, tuples: Sequence[RelationTuple], max_depth: int = 0
    ) -> list[CheckResult]:
        """Exact host-oracle evaluation of a whole batch with ZERO device
        contact (no state build, no launch) — the circuit breaker's
        graceful-degradation route and the launch watchdog's recovery
        path (api/batcher.py host_check_batch): answers stay correct
        while the device path is unhealthy, latency degrades."""
        results = [
            self.reference.check_relation_tuple(t, max_depth, self.nid)
            for t in tuples
        ]
        self.stats["host_checks"] += len(tuples)
        if self.metrics is not None and tuples:
            self.metrics.check_batch_size.observe(len(tuples))
            self.metrics.checks_total.labels("host").inc(len(tuples))
        return results

    def explain_check(self, t: RelationTuple, max_depth: int = 0, rt=None):
        """One Check with a DecisionTrace beside the verdict — the §5m
        explain plane's engine half. The DEVICE verdict stays
        authoritative: the query rides the normal submit/resolve path
        (closure probe first, BFS kernel, cause-coded host replay) with
        the explain sink recording which tier answered; a host re-walk
        (reference.explain_check, complete-walk semantics — exactly what
        the kernels implement) then reconstructs the WITNESS PATH for
        ALLOW / the exhaustion summary for DENY, and is DIFFERENTIALLY
        CHECKED against the device verdict (`witness_consistent`; a
        store write racing the re-walk sets `witness_racy` instead of
        crying wolf). Returns (CheckResult, engine trace dict) — the
        serve helper (engine/explain.py) adds the snaptoken surface.

        Deliberately the slow path: no check-cache consult (a cached
        verdict has no fresh witness), one extra exact host walk per
        call — which is why the transports admission-bound it
        (`explain.max_per_s`).

        `rt` is the TRANSPORT's RequestTrace when serving (None for
        embedders): riding the caller's trace keeps the joins this
        plane exists for — the engine spans parent-link to the
        transport root in the exported trace, the flight-recorder entry
        carries the request's trace id (`?trace_id=` filter), and the
        launch ids land on the request log / slow-query line."""
        from ..observability import RequestTrace

        if rt is None:
            rt = RequestTrace()
        sink: list = [None]
        v_before = self.manager.version(nid=self.nid)
        try:
            handle = self.check_batch_submit(
                [t], max_depth, telemetry=[rt], explain_sink=sink
            )
            results, versions = self.check_batch_resolve_v(handle)
            res, version = results[0], versions[0]
            tier_info = sink[0] or {"tier": "device"}
        except Exception:
            # a failing device path must not take explain down with it:
            # the exact host oracle answers (the breaker-degrade route's
            # semantics), tier-coded so the trace says what happened
            res = self.reference.check_relation_tuple(
                t, max_depth, self.nid
            )
            version = None
            tier_info = {"tier": "host", "cause": "engine_error"}
        if version is None:
            # host replays read the LIVE store — the answer's version is
            # the store version at resolve (same rule the check cache
            # applies to unpinned answers)
            version = self.manager.version(nid=self.nid)
        allowed = res.error is None and res.allowed
        checker = self.reference._complete_checker()
        wx = checker.explain_check(t, max_depth, self.nid)
        v_after = self.manager.version(nid=self.nid)
        racy = v_after != v_before
        consistent = res.error is None and wx["allowed"] == allowed
        if not consistent and not racy and res.error is None:
            # a quiet-store witness/verdict disagreement is exactly the
            # divergence the differential suite hunts — log it loudly
            # (the trace still reports the device verdict as the answer)
            import logging

            logging.getLogger("keto_tpu").warning(
                "explain witness mismatch: device=%s host_walk=%s "
                "tuple=%s tier=%s", allowed, wx["allowed"], t,
                tier_info.get("tier"),
            )
        from .explain import base_trace

        trace = base_trace(
            allowed=allowed,
            tier=tier_info.get("tier"),
            cause=tier_info.get("cause"),
            closure_fallback=tier_info.get("closure_fallback"),
            version=version,
            max_depth=wx.get("max_depth"),
            witness=wx.get("witness", []) if allowed else [],
            exhaustion=None if allowed else wx.get("exhaustion"),
            witness_verdict=wx["allowed"],
            witness_consistent=consistent,
            witness_racy=racy,
            stages_ms={
                k: round(v * 1e3, 3) for k, v in rt.stages.items()
            },
            launch_ids=list(rt.launch_ids),
        )
        if res.error is not None:
            trace["error"] = str(res.error)
        return res, trace

    def check_batch_submit(
        self, tuples: Sequence[RelationTuple], max_depth: int = 0,
        telemetry=None, allow_closure: bool = True, explain_sink=None,
    ):
        """Launch the device kernel for one batch WITHOUT synchronizing.

        Returns an opaque in-flight handle for check_batch_resolve. jax
        dispatch is async: the returned handle holds device futures, so a
        caller can keep several batches in flight and the device (or the
        TPU tunnel — measured ~70 ms round-trip on the axon tunnel, which
        made one-batch-at-a-time serving latency-bound) pipelines them.

        `telemetry` is an optional per-tuple list of RequestTrace|None:
        the engine's stage breakdown (assemble/dispatch at submit,
        device_wait/host_fallback at resolve) is added to every rider —
        batch-shared stages, attributed identically to each request in
        the batch — and emitted as per-request engine spans when tracing.

        `explain_sink` is an optional per-tuple list the RESOLVE phase
        fills with each query's ANSWERING TIER ({"tier": closure |
        device | host, "cause": kernel CAUSE_* for host replays}) — the
        explain plane's plumb-through. Supported for batches that fit
        one bucket (explain rides 1-item batches); oversized multi-split
        batches ignore it.
        """
        n = len(tuples)
        if n == 0:
            return ("empty", [], None)
        # flight-recorder correlation: the launch id exists BEFORE any
        # failable work (fault injection, state build, XLA compile) so a
        # submit-phase failure carries it into classify_engine_error's
        # typed CheckBatchFailedError and the auto-dump
        launch_id = next_launch_id()
        try:
            return self._check_batch_submit_inner(
                tuples, max_depth, telemetry, launch_id, allow_closure,
                explain_sink=explain_sink,
            )
        except Exception as e:
            # don't clobber an id a recursive split-slice submit already
            # stamped: the slice's id has the ring entry, not the parent's
            if getattr(e, "launch_id", None) is None:
                e.launch_id = launch_id
            raise

    def _check_batch_submit_inner(
        self, tuples: Sequence[RelationTuple], max_depth: int,
        telemetry, launch_id: int, allow_closure: bool = True,
        explain_sink=None,
    ):
        n = len(tuples)
        # fault-injection point (keto_tpu/faults.py): a stall here models
        # a wedged device/tunnel launch, an error a dying device — BEFORE
        # any state build, so the batcher's watchdog/breaker see exactly
        # what a real launch failure looks like. Disarmed: one dict miss.
        _faults.inject("device_launch")
        t_submit = time.perf_counter()
        # store outage: the breaker-open path serves this batch from the
        # existing mirror + delta overlay at its covered version (the
        # response snaptoken is the staleness bound); riders pinned to a
        # newer version are routed to the host-replay path below, where
        # the dead store answers them with the typed per-item 503
        state, degraded = self._ensure_state_degraded_ok("check")
        # marker fault (keto_tpu/faults.py mirror_corrupt): flip one bit
        # in a device table before this launch — the silent-HBM-fault
        # stand-in the anti-entropy scrubber (engine/scrub.py) must
        # detect and auto-repair. Disarmed: one dict miss.
        corrupt_spec = _faults.get("mirror_corrupt")
        if corrupt_spec is not None and corrupt_spec.should_fire():
            self.corrupt_mirror()
        global_max = self.config.max_read_depth()
        depth = max_depth if 0 < max_depth <= global_max else global_max

        B = next((b for b in self._allowed_buckets if b >= n), None)
        if B is None:
            # split oversized batches along the largest allowed bucket;
            # all slices go in flight BEFORE any synchronizes
            step = self._allowed_buckets[-1]
            return (
                "multi",
                [
                    self.check_batch_submit(
                        tuples[i : i + step], max_depth,
                        telemetry=(
                            telemetry[i : i + step] if telemetry else None
                        ),
                        allow_closure=allow_closure,
                    )
                    for i in range(0, n, step)
                ],
                None,
            )

        q_depth = np.full(B, depth, dtype=np.int32)
        if isinstance(state.snapshot.obj_slots, ArrayMap) or B > 4096:
            # vectorized batch encoding for big (ArrayMap) vocabs at any
            # size — scalar lookups cost ~1 ms each at 1e7 vocab and
            # dominated check_batch (988/s engine vs 77k/s kernel) —
            # and for LARGE batches on dict vocabs too: the scalar loop
            # scales linearly (~19 ms at B=16384 on the bench fixture,
            # serialized against the kernel launch) while the vectorized
            # path's fixed costs (list->U-array conversions, key
            # composition) amortize. Small dict batches keep the scalar
            # loop (gate is B > 4096, so the measured-scalar-faster
            # 4096 bucket stays scalar): 4.7 ms/4096 vs 7.0 ms vectorized.
            q_obj, q_rel, q_skind, q_sa, q_sb, q_valid = encode_query_batch(
                state.view, tuples, B
            )
        else:
            q_obj = np.zeros(B, dtype=np.int32)
            q_rel = np.zeros(B, dtype=np.int32)
            q_skind = np.zeros(B, dtype=np.int32)
            q_sa = np.full(B, -2, dtype=np.int32)  # sentinel: matches nothing
            q_sb = np.zeros(B, dtype=np.int32)
            q_valid = np.zeros(B, dtype=bool)

            for i, t in enumerate(tuples):
                node = state.view.encode_node(t.namespace, t.object, t.relation)
                if node is None:
                    # namespace/object/relation absent from graph+config:
                    # no edge can match, but error semantics (missing
                    # relation in a configured namespace) still apply ->
                    # exact host eval (q_valid[i] stays False, routing it
                    # to the replay loop)
                    continue
                q_obj[i], q_rel[i] = node
                subject = state.view.encode_subject(t)
                if subject is not None:
                    q_skind[i], q_sa[i], q_sb[i] = subject
                # unknown subject keeps the sentinel: traversal still runs
                # so error flags surface, but no direct probe can hit
                q_valid[i] = True

        if degraded and telemetry:
            # no-time-travel floor: a rider whose snaptoken enforcement
            # ran BEFORE the outage (min_version newer than the mirror
            # covers) must not receive a mirror answer its token would
            # claim fresher than it is — invalidating it routes it to
            # the host replay loop, where the dead store yields the
            # typed per-item StoreUnavailableError
            covered = state.covered_version
            for i, rt in enumerate(telemetry):
                mv = getattr(rt, "min_version", None)
                if mv is not None and mv > covered:
                    q_valid[i] = False

        # Leopard closure fast path: when the index covers this engine
        # state (same base snapshot, synced through covered_version), the
        # WHOLE batch rides one single-step intersection launch first —
        # chain depth stops mattering. Queries the index cannot answer
        # (uncovered/dirty/invalid) are re-submitted through the BFS
        # kernel at resolve time with cause-coded counters; host-side
        # skip causes (unbuilt/stale/lag) count here, once per query.
        # allow_closure=False is the resolve-time re-submission itself.
        if allow_closure and self.closure_enabled:
            cl_view, cl_cause = self._closure_gate(state)
            if cl_view is not None:
                from .closure_kernel import (
                    closure_kernel_packed,
                    estimate_closure_gather_bytes,
                )
                from .kernel import pack_queries

                t_launch = time.perf_counter()
                with self.tracer.span("engine.closure_launch", batch=B):
                    outputs = closure_kernel_packed(
                        cl_view.tables,
                        pack_queries(
                            q_obj, q_rel, q_depth, q_skind, q_sa, q_sb,
                            q_valid,
                        ),
                        cc_probes=cl_view.cc_probes,
                        ch_probes=cl_view.ch_probes,
                        has_dirty=cl_view.has_dirty,
                    )
                t_done = time.perf_counter()
                return (
                    "closure",
                    outputs,
                    {
                        "state": state,
                        "tuples": tuples,
                        "n": n,
                        "B": B,
                        "max_depth": max_depth,
                        "q_valid": q_valid,
                        "stage_s": {
                            "assemble": t_launch - t_submit,
                            "dispatch": t_done - t_launch,
                        },
                        "telemetry": telemetry,
                        "explain_sink": explain_sink,
                        "launch_id": launch_id,
                        "t_submit": t_submit,
                        "kind": "closure",
                        "step_cap": 1,
                        "gather_step_bytes": estimate_closure_gather_bytes(
                            B, cl_view.cc_probes, cl_view.ch_probes,
                            cl_view.has_dirty,
                        ),
                    },
                )
            if cl_cause is not None:
                self._count_closure_fallback(cl_cause, n)

        # per-launch frontier sizing: every BFS step's cost scales with the
        # frontier length, not the query count, so a small bucket must not
        # pay the full-size frontier (a 16-query launch at F=16384 costs
        # the same ~130 ms as a 4096-query one). Small buckets get a
        # proportional frontier; queries whose exploration outgrows it are
        # flagged needs_host and replayed exactly — a safe (slower) path.
        if self.auto_frontier:
            # 4x headroom over the seed tasks; measured on the serve path
            # (1-core CPU host): B=16 at F=64 is 0.2 ms/launch vs 1.6 ms
            # at the old 1024 floor — small-batch serve latency is the
            # launch cost, so the floor must scale with the bucket
            launch_cap = min(self.frontier_cap, max(4 * B, 64))
        else:
            launch_cap = self.frontier_cap

        # islands: one ctx block of K leaves per instance; cap scales with
        # the batch so island-heavy workloads don't immediately overflow
        # to host replay (overflow is safe, just slow)
        island_cap = 2 * B if state.snapshot.island_circuits else 0
        t_launch = time.perf_counter()
        n_shards = 1
        with self.tracer.span(
            "engine.kernel_launch", batch=B, frontier=launch_cap
        ):
            if self.mesh is not None:
                from ..parallel.kernel import (
                    sharded_check_kernel,
                    sharded_static_config,
                )

                statics = sharded_static_config(
                    state.sharded, global_max, launch_cap,
                    n_island_cap=island_cap, has_delta=state.has_delta,
                )
                # dict view of the statics tuple for the gather-bytes
                # estimate (each shard runs the full per-step gather set
                # over its own tables)
                cfg = dict(zip(_KERNEL_STATICS, statics))
                n_shards = int(self.mesh.devices.size)
                sharded_tables, replicated_tables = state.tables
                outputs = sharded_check_kernel(
                    self.mesh, sharded_tables, replicated_tables,
                    q_obj, q_rel, q_depth, q_skind, q_sa, q_sb, q_valid,
                    statics=statics, axis=self.mesh.axis_names[0],
                )
            else:
                from .kernel import check_kernel_packed, pack_queries

                cfg = kernel_static_config(
                    state.snapshot, global_max, launch_cap,
                    n_island_cap=island_cap, has_delta=state.has_delta,
                )
                # single-buffer I/O: ONE host->device upload (the packed
                # query array) and ONE device->host readback at resolve.
                # Through the axon tunnel every buffer transfer pays its
                # own round-trip; seven uploads + five readbacks per
                # batch, not kernel compute, dominated the r04 first
                # capture (~300 ms/batch at ~µs-scale primitives).
                outputs = check_kernel_packed(
                    state.tables,
                    pack_queries(
                        q_obj, q_rel, q_depth, q_skind, q_sa, q_sb, q_valid
                    ),
                    **cfg,
                )
        # everything past the launch is deferred to resolve: touching the
        # outputs here would block on the device round-trip
        t_done = time.perf_counter()
        return (
            "batch",
            outputs,
            {
                "state": state,
                "tuples": tuples,
                "n": n,
                "B": B,
                "max_depth": max_depth,
                "q_valid": q_valid,
                "island_cap": island_cap if self.mesh is None else None,
                # per-stage seconds accumulated so far; resolve adds
                # device_wait / host_fallback and finalizes attribution
                "stage_s": {
                    "assemble": t_launch - t_submit,
                    "dispatch": t_done - t_launch,
                },
                "telemetry": telemetry,
                "explain_sink": explain_sink,
                # flight-recorder fields, read back at the resolve sync
                # point together with the device stats vector
                "launch_id": launch_id,
                "t_submit": t_submit,
                "launch_cap": launch_cap,
                "step_cap": int(cfg["max_steps"]),
                "gather_step_bytes": (
                    n_shards * estimate_step_gather_bytes(cfg)
                ),
            },
        )

    def check_batch_resolve(self, handle) -> list[CheckResult]:
        """Synchronize one in-flight batch and produce its CheckResults
        (device readback + island combine + exact host replays)."""
        return self.check_batch_resolve_v(handle)[0]

    def check_batch_resolve_v(self, handle):
        """check_batch_resolve with version plumb-through: returns
        (results, versions) where versions[i] is the store version the
        answer is authoritative at — the evaluated state's
        covered_version for device-path answers — or None for
        host-replayed items (the replay reads the LIVE store, so its
        answer is not pinned to any particular version). The serve-side
        check cache (api/check_cache.py) stores verdicts at exactly
        these versions; None falls back to its raced-write re-check."""
        kind, outputs, meta = handle
        if kind == "empty":
            return [], []
        if kind == "multi":
            results: list[CheckResult] = []
            versions: list = []
            for h in outputs:
                r, v = self.check_batch_resolve_v(h)
                results.extend(r)
                versions.extend(v)
            return results, versions
        if kind == "closure":
            try:
                return self._closure_batch_resolve_v(outputs, meta)
            except Exception as e:
                # a failing leftover re-submission already stamped its
                # own launch id — that id has the ring entry
                if getattr(e, "launch_id", None) is None:
                    e.launch_id = meta.get("launch_id")
                raise
        try:
            return self._check_batch_resolve_v_inner(outputs, meta)
        except Exception as e:
            # resolve-phase failures carry the launch id into the typed
            # error surface and the flight-recorder dump
            e.launch_id = meta.get("launch_id")
            raise

    def _closure_batch_resolve_v(self, outputs, meta):
        """Synchronize one closure launch: read the intersection verdicts
        back, answer every resolved query at the view's (== the state's)
        covered version, and re-submit the cause-coded remainder through
        the BFS kernel (allow_closure=False — exactly one closure attempt
        per batch). The common serving case resolves the whole batch here
        with zero BFS contact."""
        from .closure_kernel import CL_CAUSE_NAMES, unpack_closure_results

        state = meta["state"]
        tuples = meta["tuples"]
        n, B, max_depth = meta["n"], meta["B"], meta["max_depth"]
        telemetry = meta.get("telemetry")
        t_resolve = time.perf_counter()
        member, cause, stats = unpack_closure_results(
            # ketolint: allow[host-sync] reason=this IS the closure batch's designated sync point: one packed readback carries verdicts, causes, and the launch stats vector — the same single-transfer resolve contract as every other kernel
            np.asarray(outputs), B,
        )
        device_wait_s = time.perf_counter() - t_resolve

        sink = meta.get("explain_sink")
        results: list = [None] * n
        versions: list = [None] * n
        covered = state.covered_version
        leftover: list[int] = []
        leftover_cause: dict[int, str] = {}
        causes: dict[str, int] = {}
        for i in range(n):
            c = int(cause[i])
            if c == 0:
                results[i] = (
                    RESULT_IS_MEMBER if member[i] else RESULT_NOT_MEMBER
                )
                versions[i] = covered
                if sink is not None:
                    sink[i] = {"tier": "closure"}
                if telemetry is not None and telemetry[i] is not None:
                    telemetry[i].tier = "closure"
            else:
                leftover.append(i)
                name = CL_CAUSE_NAMES.get(c, "uncovered")
                leftover_cause[i] = name
                causes[name] = causes.get(name, 0) + 1
        n_hits = n - len(leftover)
        self.stats["closure_hits"] = (
            self.stats.get("closure_hits", 0) + n_hits
        )
        if self.metrics is not None:
            if n_hits:
                self.metrics.closure_hits_total.inc(n_hits)
                self.metrics.checks_total.labels("device").inc(n_hits)
            self.metrics.check_batch_size.observe(n)
        self.stats["device_checks"] += n_hits
        for name, cnt in causes.items():
            self._count_closure_fallback(name, cnt)

        meta["closure_resolved"] = n_hits
        self._finish_check_stages(
            meta, device_wait_s, 0.0, n, B, stats=stats, host_causes=causes
        )
        if leftover:
            sub_sink = [None] * len(leftover) if sink is not None else None
            sub_handle = self.check_batch_submit(
                [tuples[i] for i in leftover],
                max_depth,
                telemetry=(
                    [telemetry[i] for i in leftover] if telemetry else None
                ),
                allow_closure=False,
                explain_sink=sub_sink,
            )
            sub_res, sub_ver = self.check_batch_resolve_v(sub_handle)
            for j, i in enumerate(leftover):
                results[i] = sub_res[j]
                versions[i] = sub_ver[j]
                if sink is not None:
                    info = dict(sub_sink[j] or {"tier": "device"})
                    # the explain trace says WHY the closure probe
                    # declined this query before the BFS ride answered
                    info["closure_fallback"] = leftover_cause.get(i)
                    sink[i] = info
        return results, versions

    def _check_batch_resolve_v_inner(self, outputs, meta):
        state = meta["state"]
        tuples = meta["tuples"]
        n, B, max_depth = meta["n"], meta["B"], meta["max_depth"]
        q_valid = meta["q_valid"]
        t_resolve = time.perf_counter()
        if meta.get("island_cap") is not None:
            # packed single-device result: ONE device->host readback —
            # the launch stats vector rides the same transfer
            from .kernel import unpack_results

            ctx_hit, needs_host, isl_parent, isl_pid, n_isl, stats = (
                unpack_results(
                    # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
                    np.asarray(outputs), B, meta["island_cap"],
                    state.snapshot.K,
                )
            )
            ctx_hit = ctx_hit.copy()
        else:
            ctx_hit, needs_host, isl_parent, isl_pid, n_isl, stats = outputs
            # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
            ctx_hit = np.asarray(ctx_hit).copy()
            # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
            needs_host = np.asarray(needs_host)
            # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
            n_isl = int(n_isl)
            # ketolint: allow[host-sync] reason=part of the same designated resolve sync point: the mesh path's replicated stats vector reads back with the batch results, not as an extra round-trip
            stats = np.asarray(stats)
        if _faults.get("batch_corrupt") is not None:
            # fault-injection point: poison every slot's device verdict
            # so each query takes the exact-host-replay escape hatch the
            # capacity overflows use — answers must stay byte-correct
            _faults.inject("batch_corrupt")
            # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
            needs_host = np.maximum(np.asarray(needs_host), 1)
        if n_isl:
            from .islands import combine_islands

            member = combine_islands(
                # ketolint: allow[host-sync] reason=this IS the batch's designated sync point: resolve is the synchronize phase of the split-phase submit/resolve contract, and the single-buffer I/O design makes this readback the ONE device->host transfer for the whole batch
                ctx_hit, np.asarray(isl_parent), np.asarray(isl_pid),
                n_isl, state.snapshot.island_circuits, B, state.snapshot.K,
            )
        else:
            member = ctx_hit[:B]
        device_wait_s = time.perf_counter() - t_resolve

        # fast path: every query ran on device (the steady serving
        # state) — one numpy reduction decides, then results come from a
        # bare list comprehension over the verdict array instead of the
        # per-item bookkeeping loop (~3x less host time per batch, and
        # the host loop serializes against the next launch's encode)
        sink = meta.get("explain_sink")
        telemetry = meta.get("telemetry")
        if (
            n <= B
            and bool(q_valid[:n].all())
            and not bool((needs_host[:n] > 0).any())
        ):
            with self.tracer.span("engine.resolve_batch", batch=n) as sp:
                sp.set_attribute("host_replays", 0)
                results = [
                    RESULT_IS_MEMBER if m else RESULT_NOT_MEMBER
                    for m in member[:n].tolist()
                ]
            if sink is not None:
                for i in range(n):
                    sink[i] = {"tier": "device"}
            if telemetry is not None:
                for rt in telemetry:
                    if rt is not None:
                        rt.tier = "device"
            self.stats["device_checks"] += n
            if self.metrics is not None:
                self.metrics.check_batch_size.observe(n)
                self.metrics.checks_total.labels("device").inc(n)
            self._finish_check_stages(
                meta, device_wait_s, 0.0, n, B, stats=stats
            )
            return results, [state.covered_version] * n

        results = []
        versions: list = []
        covered = state.covered_version
        n_host = 0
        host_s = 0.0
        host_causes: dict[str, int] = {}
        # identical host-replayed queries within one batch evaluate once
        # (an adversarial batch of 4096 same-tuple fallbacks would
        # otherwise serialize 4096 recursive walks)
        replay_memo: dict[tuple, CheckResult] = {}
        with self.tracer.span("engine.resolve_batch", batch=n) as sp:
            for i, t in enumerate(tuples):
                if i < B and q_valid[i] and not needs_host[i]:
                    # shared immutable singletons: 4096 CheckResult
                    # constructions per batch are measurable on the
                    # 1-core serve host
                    results.append(
                        RESULT_IS_MEMBER if member[i] else RESULT_NOT_MEMBER
                    )
                    versions.append(covered)
                    if sink is not None:
                        sink[i] = {"tier": "device"}
                    if telemetry is not None and telemetry[i] is not None:
                        telemetry[i].tier = "device"
                else:
                    n_host += 1
                    # cause bookkeeping: the kernel reports a CAUSE_* code
                    # per query; queries that never reached the device
                    # (unknown vocabulary) count as "unindexed"
                    if i < B and q_valid[i]:
                        cause = CAUSE_NAMES.get(
                            int(needs_host[i]), CAUSE_NAME_UNINDEXED
                        )
                    else:
                        cause = CAUSE_NAME_UNINDEXED
                    host_causes[cause] = host_causes.get(cause, 0) + 1
                    # field-structured key: the display string is NOT
                    # injective (a subject_id spelled "(ns:obj#rel)"
                    # renders like a real subject set)
                    key = (
                        t.namespace, t.object, t.relation, t.subject_id,
                        t.subject_set, max_depth,
                    )
                    res = replay_memo.get(key)
                    if res is None:
                        t_host = time.perf_counter()
                        res = self.reference.check_relation_tuple(
                            t, max_depth, self.nid
                        )
                        host_s += time.perf_counter() - t_host
                        replay_memo[key] = res
                    results.append(res)
                    versions.append(None)
                    if sink is not None:
                        sink[i] = {"tier": "host", "cause": cause}
                    if telemetry is not None and telemetry[i] is not None:
                        telemetry[i].tier = "host"
            sp.set_attribute("host_replays", n_host)
        self.stats["device_checks"] += n - n_host
        self.stats["host_checks"] += n_host
        for cause, cnt in host_causes.items():
            self.stats["host_cause"][cause] = (
                self.stats["host_cause"].get(cause, 0) + cnt
            )
        if self.metrics is not None:
            self.metrics.check_batch_size.observe(n)
            self.metrics.checks_total.labels("device").inc(n - n_host)
            if n_host:
                self.metrics.checks_total.labels("host").inc(n_host)
            for cause, cnt in host_causes.items():
                self.metrics.host_fallback_total.labels(cause).inc(cnt)
        self._finish_check_stages(
            meta, device_wait_s, host_s, n, B,
            stats=stats, host_causes=host_causes,
        )
        return results, versions

    def _finish_check_stages(
        self, meta, device_wait_s: float, host_s: float, n: int, B: int,
        stats=None, host_causes=None,
    ) -> None:
        """Finalize one batch's stage attribution: per-stage histogram
        samples (once per batch), the occupancy gauge, each rider's
        RequestTrace stages (+ launch id), the flight-recorder entry,
        and per-request engine spans when tracing. Batch-shared stages
        are attributed identically to every rider — the breakdown says
        where the BATCH spent its time, which is what a tail-latency
        investigation needs."""
        stage_s = dict(meta.get("stage_s") or ())
        stage_s["device_wait"] = device_wait_s
        if host_s > 0.0:
            stage_s["host_fallback"] = host_s
        telemetry = meta.get("telemetry")
        if self.metrics is not None:
            # exemplar: the first rider's trace id rides the stage
            # histogram buckets (OpenMetrics exemplars — the metrics ->
            # trace join); batch-shared stages observe once, so one
            # representative trace id per batch is the honest grain
            exemplar_tid = None
            for rt in (telemetry or ()):
                if rt is not None:
                    exemplar_tid = rt.ctx.trace_id
                    break
            for name, dur in stage_s.items():
                self.metrics.observe_stage(name, dur, trace_id=exemplar_tid)
            self.metrics.batch_occupancy.set(n / B if B else 1.0)
        self._record_launch(meta, stats, n, B, host_causes, stage_s)
        if not telemetry:
            return
        spans = getattr(self.tracer, "active", False)
        launch_id = meta.get("launch_id")
        for rt in telemetry:
            if rt is None:
                continue
            if launch_id is not None:
                ids = getattr(rt, "launch_ids", None)
                if ids is not None:
                    ids.append(launch_id)
            for name, dur in stage_s.items():
                rt.add_stage(name, dur)
                if spans:
                    # launch_id rides the span: the OTLP exporter turns
                    # it into a `flightrec.launch` span EVENT, so a
                    # trace at the collector points at its ring entry
                    self.tracer.record(
                        f"engine.{name}", ctx=rt.ctx, duration_s=dur,
                        batch=B, launch_id=launch_id,
                    )

    def _record_launch(
        self, meta, stats, n: int, B: int, host_causes, stage_s
    ) -> None:
        """One flight-recorder entry + the keto_tpu_launch_* metric
        samples for a resolved device batch. Everything here is host
        arithmetic over the counters that rode the batch's existing
        readback — no extra device contact."""
        sd = launch_stats_dict(stats) if stats is not None else {}
        step_cap = int(meta.get("step_cap", 0))
        gather_bytes = sd.get("steps", 0) * int(
            meta.get("gather_step_bytes", 0)
        )
        occupancy = (n / B) if B else 1.0
        if self.metrics is not None and sd:
            self.metrics.observe_launch(
                sd["steps"], step_cap, sd["frontier_max"], gather_bytes,
                sd["edge_rows"], round(1.0 - occupancy, 4),
            )
        fr = self.flightrec
        if fr is None or not fr.enabled:
            return
        t_submit = meta.get("t_submit")
        entry = {
            "launch_id": meta.get("launch_id"),
            "kind": meta.get("kind", "check"),
            "nid": self.nid,
            "bucket": B,
            "n": n,
            "occupancy": round(occupancy, 4),
            "frontier_cap": meta.get("launch_cap"),
            "step_cap": step_cap,
            "gather_bytes_est": gather_bytes,
            "host_causes": dict(host_causes or {}),
            "trace_ids": [
                rt.ctx.trace_id
                for rt in (meta.get("telemetry") or ())
                if rt is not None
            ],
            "stage_ms": {
                k: round(v * 1e3, 3) for k, v in stage_s.items()
            },
            **sd,
        }
        if "closure_resolved" in meta:
            entry["closure_resolved"] = meta["closure_resolved"]
        if t_submit is not None:
            entry["wall_ms"] = round(
                (time.perf_counter() - t_submit) * 1e3, 3
            )
        fr.record(entry)
