"""Host reference engine tests, porting the reference's engine_test.go
(max-depth precedence, direct/indirect, transitivity rejection, wide and
circular graphs) and rewrites_test.go (the full namespace fixture set and
query->expected table, incl. and/not)."""

import pytest

from keto_tpu.config import Config
from keto_tpu.engine import Membership, ReferenceEngine
from keto_tpu.errors import RelationNotFoundError
from keto_tpu.ketoapi import RelationTuple, SubjectSet, TreeNodeType
from keto_tpu.namespace import Namespace
from keto_tpu.namespace.ast import (
    ComputedSubjectSet,
    InvertResult,
    Operator,
    Relation,
    SubjectSetRewrite,
    TupleToSubjectSet,
)
from keto_tpu.storage import MemoryManager


def make_engine(namespaces, tuples, max_depth=5):
    cfg = Config({"limit": {"max_read_depth": max_depth}})
    cfg.set_namespaces(namespaces)
    m = MemoryManager()
    m.write_relation_tuples([RelationTuple.from_string(s) for s in tuples])
    return ReferenceEngine(m, cfg), cfg


def check(e, s, depth=0):
    return e.check_is_member(RelationTuple.from_string(s), depth)


class TestEngine:
    """ref: internal/check/engine_test.go:69-520"""

    def test_respects_max_depth(self):
        # ref: engine_test.go:72-116 — access via owner via admin needs
        # depth 3; global default 5
        e, cfg = make_engine(
            [Namespace(name="test")],
            [
                "test:object#admin@user",
                "test:object#owner@(test:object#admin)",
                "test:object#access@(test:object#owner)",
            ],
        )
        assert cfg.max_read_depth() == 5
        # request depth takes precedence; 2 is not enough, 3 is
        assert not check(e, "test:object#access@user", 2)
        assert check(e, "test:object#access@user", 3)
        # global max-depth takes precedence when lesser
        cfg.set("limit.max_read_depth", 2)
        assert not check(e, "test:object#access@user", 3)
        cfg.set("limit.max_read_depth", 3)
        assert check(e, "test:object#access@user", 0)

    def test_direct_inclusion(self):
        e, _ = make_engine([Namespace(name="n")], ["n:obj#access@user"])
        assert check(e, "n:obj#access@user")

    def test_indirect_inclusion_level_1(self):
        # ref: engine_test.go:136-173 (producer-of-dust subject set)
        e, _ = make_engine(
            [Namespace(name="sofa")],
            [
                "sofa:dust#remove@(sofa:dust#producer)",
                "sofa:dust#producer@mark",
            ],
        )
        assert check(e, "sofa:dust#remove@mark")

    def test_direct_exclusion(self):
        e, _ = make_engine([Namespace(name="n")], ["n:obj#rel@user"])
        assert not check(e, "n:obj#rel@other-user")

    def test_wrong_object(self):
        e, _ = make_engine([Namespace(name="n")], ["n:obj#rel@user"])
        assert not check(e, "n:other-obj#rel@user")

    def test_wrong_relation(self):
        e, _ = make_engine([Namespace(name="n")], ["n:obj#rel@user"])
        assert not check(e, "n:obj#other-rel@user")

    def test_indirect_inclusion_level_2(self):
        # ref: engine_test.go:267-331 (org -> dir -> file chains)
        e, _ = make_engine(
            [Namespace(name="obj")],
            [
                "obj:file#parent@(obj:directory#parent)",
                "obj:directory#parent@(obj:org#member)",
                "obj:org#member@user",
            ],
        )
        assert check(e, "obj:file#parent@user")
        assert check(e, "obj:directory#parent@user")

    def test_rejects_transitive_relation(self):
        # ref: engine_test.go:333-371 — access via "no relation" must not
        # leak: tuples obj#tr@(obj2#some) and obj2#not_some@user
        e, _ = make_engine(
            [Namespace(name="n")],
            [
                "n:obj#rel@(n:obj2#some-rel)",
                "n:obj2#not-some-rel@user",
            ],
        )
        assert not check(e, "n:obj#rel@user")

    def test_subject_id_next_to_subject_set(self):
        # ref: engine_test.go:373-424 — both a direct subject id and a
        # subject set on the same (obj, rel)
        e, _ = make_engine(
            [Namespace(name="n")],
            [
                "n:o#r@direct-user",
                "n:o#r@(n:o2#r2)",
                "n:o2#r2@indirect-user",
            ],
        )
        assert check(e, "n:o#r@direct-user")
        assert check(e, "n:o#r@indirect-user")
        # a subject-set subject checks directly (exact match)
        assert e.check_is_member(
            RelationTuple.make("n", "o", "r", SubjectSet("n", "o2", "r2"))
        )

    def test_wide_tuple_graph(self):
        # ref: engine_test.go:426-466
        tuples = []
        for i in range(10):
            tuples.append(f"n:o#r@(n:o-{i}#r-{i})")
        tuples.append("n:o-7#r-7@user")
        e, _ = make_engine([Namespace(name="n")], tuples)
        assert check(e, "n:o#r@user")
        assert not check(e, "n:o#r@other")

    def test_circular_tuples(self):
        # ref: engine_test.go:468-520 — a cycle user-a <-> user-b must
        # terminate and answer correctly
        e, _ = make_engine(
            [Namespace(name="n")],
            [
                "n:user-a#friend@(n:user-b#friend)",
                "n:user-b#friend@(n:user-a#friend)",
                "n:user-a#friend@user-x",
            ],
            max_depth=10,
        )
        assert check(e, "n:user-a#friend@user-x")
        assert check(e, "n:user-b#friend@user-x")
        assert not check(e, "n:user-a#friend@nobody")

    def test_wildcard_relation_not_expanded(self):
        # subject sets with relation "..." are not expanded by
        # expand-subject (engine.go:124)
        e, _ = make_engine(
            [Namespace(name="n")],
            [
                "n:o#r@(n:o2#...)",
                "n:o2#any@user",
            ],
        )
        assert not check(e, "n:o#r@user")

    def test_unknown_namespace_is_not_member_not_error(self):
        e, _ = make_engine([Namespace(name="n")], [])
        assert not check(e, "other:o#r@user")

    def test_missing_relation_with_config_is_error(self):
        e, _ = make_engine(
            [Namespace(name="n", relations=[Relation(name="known")])], []
        )
        with pytest.raises(RelationNotFoundError):
            check(e, "n:o#unknown@user")


# The rewrites fixture set, ported from rewrites_test.go:20-128
REWRITE_NAMESPACES = [
    Namespace(
        name="doc",
        relations=[
            Relation(name="owner"),
            Relation(
                name="editor",
                subject_set_rewrite=SubjectSetRewrite(
                    children=[ComputedSubjectSet(relation="owner")]
                ),
            ),
            Relation(
                name="viewer",
                subject_set_rewrite=SubjectSetRewrite(
                    children=[
                        ComputedSubjectSet(relation="editor"),
                        TupleToSubjectSet(
                            relation="parent",
                            computed_subject_set_relation="viewer",
                        ),
                    ]
                ),
            ),
        ],
    ),
    Namespace(name="group", relations=[Relation(name="member")]),
    Namespace(name="level", relations=[Relation(name="member")]),
    Namespace(
        name="resource",
        relations=[
            Relation(name="level"),
            Relation(
                name="viewer",
                subject_set_rewrite=SubjectSetRewrite(
                    children=[
                        TupleToSubjectSet(
                            relation="owner", computed_subject_set_relation="member"
                        )
                    ]
                ),
            ),
            Relation(
                name="owner",
                subject_set_rewrite=SubjectSetRewrite(
                    children=[
                        TupleToSubjectSet(
                            relation="owner", computed_subject_set_relation="member"
                        )
                    ]
                ),
            ),
            Relation(
                name="read",
                subject_set_rewrite=SubjectSetRewrite(
                    children=[
                        ComputedSubjectSet(relation="viewer"),
                        ComputedSubjectSet(relation="owner"),
                    ]
                ),
            ),
            Relation(
                name="update",
                subject_set_rewrite=SubjectSetRewrite(
                    children=[ComputedSubjectSet(relation="owner")]
                ),
            ),
            Relation(
                name="delete",
                subject_set_rewrite=SubjectSetRewrite(
                    operation=Operator.AND,
                    children=[
                        ComputedSubjectSet(relation="owner"),
                        TupleToSubjectSet(
                            relation="level", computed_subject_set_relation="member"
                        ),
                    ],
                ),
            ),
        ],
    ),
    Namespace(
        name="acl",
        relations=[
            Relation(name="allow"),
            Relation(name="deny"),
            Relation(
                name="access",
                subject_set_rewrite=SubjectSetRewrite(
                    operation=Operator.AND,
                    children=[
                        ComputedSubjectSet(relation="allow"),
                        InvertResult(child=ComputedSubjectSet(relation="deny")),
                    ],
                ),
            ),
        ],
    ),
]

REWRITE_TUPLES = [
    "doc:document#owner@user",
    "doc:doc_in_folder#parent@(doc:folder#...)",
    "doc:folder#owner@user",
    "doc:file#parent@(doc:folder_c#...)",
    "doc:folder_c#parent@(doc:folder_b#...)",
    "doc:folder_b#parent@(doc:folder_a#...)",
    "doc:folder_a#owner@user",
    "group:editors#member@mark",
    "level:superadmin#member@mark",
    "level:superadmin#member@sandy",
    "resource:topsecret#owner@(group:editors#...)",
    "resource:topsecret#level@(level:superadmin#...)",
    "resource:topsecret#owner@mike",
    "acl:document#allow@alice",
    "acl:document#allow@bob",
    "acl:document#allow@mallory",
    "acl:document#deny@mallory",
]

# (query, expected-is-member), ported from rewrites_test.go:130-215
REWRITE_CASES = [
    ("doc:document#owner@user", True),
    ("doc:document#editor@user", True),
    ("doc:document#viewer@user", True),
    ("doc:document#editor@nobody", False),
    ("doc:folder#viewer@user", True),
    ("doc:doc_in_folder#viewer@user", True),
    ("doc:doc_in_folder#viewer@nobody", False),
    ("doc:another_doc#viewer@user", False),
    ("doc:file#viewer@user", True),
    ("level:superadmin#member@mark", True),
    ("resource:topsecret#owner@mark", True),
    ("resource:topsecret#delete@mark", True),
    ("resource:topsecret#update@mike", True),
    ("level:superadmin#member@mike", False),
    ("resource:topsecret#delete@mike", False),
    ("resource:topsecret#delete@sandy", False),
    ("acl:document#access@alice", True),
    ("acl:document#access@bob", True),
    ("acl:document#allow@mallory", True),
    ("acl:document#access@mallory", False),
]


@pytest.fixture(scope="module")
def rewrite_engine():
    cfg = Config({"limit": {"max_read_depth": 100}})
    cfg.set_namespaces(REWRITE_NAMESPACES)
    m = MemoryManager()
    m.write_relation_tuples([RelationTuple.from_string(s) for s in REWRITE_TUPLES])
    return ReferenceEngine(m, cfg)


class TestUsersetRewrites:
    @pytest.mark.parametrize("query,expected", REWRITE_CASES)
    def test_cases(self, rewrite_engine, query, expected):
        res = rewrite_engine.check_relation_tuple(
            RelationTuple.from_string(query), 100
        )
        assert res.error is None
        assert (res.membership == Membership.IS_MEMBER) == expected, query

    def test_proof_tree_for_intersection(self, rewrite_engine):
        # ported path assertion: delete@mark -> {level member, owner->editors}
        res = rewrite_engine.check_relation_tuple(
            RelationTuple.from_string("resource:topsecret#delete@mark"), 100
        )
        assert res.membership == Membership.IS_MEMBER
        labels = tree_labels(res.tree)
        assert "level:superadmin#member@mark" in labels
        assert "group:editors#member@mark" in labels

    def test_proof_tree_direct(self, rewrite_engine):
        res = rewrite_engine.check_relation_tuple(
            RelationTuple.from_string("acl:document#access@alice"), 100
        )
        assert res.membership == Membership.IS_MEMBER
        assert "acl:document#allow@alice" in tree_labels(res.tree)


def tree_labels(tree):
    if tree is None:
        return []
    out = [tree.label()]
    for c in tree.children:
        out.extend(tree_labels(c))
    return out


class TestExpand:
    """ref: internal/expand engine + handler behavior."""

    def test_expand_union_tree(self):
        e, _ = make_engine(
            [Namespace(name="n")],
            [
                "n:o#r@u1",
                "n:o#r@u2",
                "n:o#r@(n:o2#r)",
                "n:o2#r@nested",
            ],
            max_depth=10,
        )
        tree = e.expand(SubjectSet("n", "o", "r"), 10)
        assert tree.type == TreeNodeType.UNION
        subjects = set()
        for child in tree.children:
            t = child.tuple
            subjects.add(t.subject_id or str(t.subject_set))
        assert subjects == {"u1", "u2", "n:o2#r"}
        nested = [c for c in tree.children if c.type == TreeNodeType.UNION]
        assert len(nested) == 1
        assert nested[0].children[0].tuple.subject_id == "nested"

    def test_expand_depth_cap_leaf(self):
        e, _ = make_engine(
            [Namespace(name="n")], ["n:o#r@u1", "n:o#r@(n:o2#r)"], max_depth=10
        )
        tree = e.expand(SubjectSet("n", "o", "r"), 1)
        assert tree.type == TreeNodeType.LEAF

    def test_expand_no_tuples_is_none(self):
        e, _ = make_engine([Namespace(name="n")], [])
        assert e.expand(SubjectSet("n", "o", "r"), 5) is None

    def test_expand_subject_id_is_leaf(self):
        e, _ = make_engine([Namespace(name="n")], [])
        tree = e.expand("just-a-user", 5)
        assert tree.type == TreeNodeType.LEAF

    def test_expand_cycle_terminates(self):
        e, _ = make_engine(
            [Namespace(name="n")],
            [
                "n:a#r@(n:b#r)",
                "n:b#r@(n:a#r)",
                "n:a#r@direct",
            ],
            max_depth=10,
        )
        tree = e.expand(SubjectSet("n", "a", "r"), 10)
        assert tree is not None
        labels = tree_labels(tree)
        assert any("direct" in l for l in labels)


class TestVisitedPruningModes:
    def test_prune_free_mode_explores_more(self):
        # graph where visited pruning can matter: diamond reaching the same
        # subject set twice
        namespaces = [Namespace(name="n")]
        tuples = [
            "n:root#r@(n:mid1#r)",
            "n:root#r@(n:mid2#r)",
            "n:mid1#r@(n:deep#r)",
            "n:mid2#r@(n:deep#r)",
            "n:deep#r@user",
        ]
        e1, _ = make_engine(namespaces, tuples, max_depth=10)
        e2, _ = make_engine(namespaces, tuples, max_depth=10)
        e2.visited_pruning = False
        assert check(e1, "n:root#r@user")
        assert check(e2, "n:root#r@user")


class TestVisitedKeyInjectivity:
    def test_plain_id_textually_equal_to_subject_set_does_not_prune(self):
        # a plain subject_id that LOOKS like a subject set's canonical
        # string must not poison the visited set (reference keys by UUID,
        # which cannot collide across subject kinds)
        e, _ = make_engine(
            [Namespace(name="n")],
            [],
            max_depth=10,
        )
        e.manager.write_relation_tuples([
            RelationTuple("n", "root", "r", subject_id="n:deep0#r"),
            RelationTuple("n", "root", "r",
                          subject_set=SubjectSet("n", "deep0", "r")),
            RelationTuple("n", "deep0", "r", subject_id="user"),
        ])
        assert check(e, "n:root#r@user")
