"""REST API: Keto-compatible HTTP routes on stdlib ThreadingHTTPServer.

Route/behavior parity (ref files in internal/):
  read router (:4466)  — GET /relation-tuples (relationtuple/read_server.go:122-175),
    GET+POST /relation-tuples/check and .../check/openapi — the bare routes
    mirror the check status as 403-on-deny, the /openapi variants always
    200 (check/handler.go:49-55, :129-142, :183-226); GET
    /relation-tuples/expand (expand/handler.go:43-107)
  write router (:4467) — PUT /admin/relation-tuples -> 201 + Location +
    echoed tuple (transact_server.go:105-133), DELETE by URL query -> 204
    (:152-181), PATCH with [{action, relation_tuple}] deltas -> 204
    (:211-252)
  both                 — /health/alive, /health/ready, /version (healthx)
  metrics (:4468)      — GET /metrics/prometheus (prometheusx path)

Error bodies use the herodot JSON shape {"error": {code, status, message}}
via KetoError.to_dict. Unknown namespaces on the REST check path answer
{"allowed": false} instead of erroring (check/handler.go:156-161) — unlike
gRPC, which propagates NOT_FOUND.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..errors import KetoError, MalformedInputError, NamespaceNotFoundError
from ..observability import (
    RequestTrace,
    finish_request_telemetry,
    parse_traceparent,
    reset_request_trace,
    set_request_trace,
)
from ..ketoapi import (
    GetResponse,
    PatchDelta,
    RelationQuery,
    RelationTuple,
    SubjectSet,
)

READ_ROUTE_BASE = "/relation-tuples"
CHECK_ROUTE_BASE = "/relation-tuples/check"
CHECK_OPENAPI_ROUTE = "/relation-tuples/check/openapi"
# keto_tpu extension beside the parity surface: POST an ARRAY of tuples,
# get per-item verdicts in one round-trip (the reference has no batch
# check API — check/handler.go resolves one tuple per request)
CHECK_BATCH_ROUTE = "/relation-tuples/check/batch"
EXPAND_ROUTE = "/relation-tuples/expand"
# keto_tpu reverse-reachability extension (engine/reverse_kernel.py):
# "which objects can this subject reach" / "which subjects reach this
# object" — the reference has no such routes (Zanzibar's Leopard family)
LIST_OBJECTS_ROUTE = "/relation-tuples/list-objects"
LIST_SUBJECTS_ROUTE = "/relation-tuples/list-subjects"
# keto_tpu bulk-ACL-filter extension (engine/filter_kernel.py): POST a
# candidate object column, get back the subset the subject can see —
# search-result filtering (Zanzibar's dominant workload) as ONE request
FILTER_ROUTE = "/relation-tuples/filter"
# keto_tpu watch extension (keto_tpu/watch): the streaming changelog as
# Server-Sent Events — Zanzibar's Watch API (§2.4.3), absent from the
# reference
WATCH_ROUTE = "/relation-tuples/watch"
WRITE_ROUTE_BASE = "/admin/relation-tuples"
ALIVE_PATH = "/health/alive"
READY_PATH = "/health/ready"
VERSION_PATH = "/version"
METRICS_PATH = "/metrics/prometheus"
# on-demand capture admin (metrics listener only — the operator plane):
# POST starts a cpu/mem/jax capture against the RUNNING serve, POST
# .../stop writes the artifact; see keto_tpu/profiling.py
PROFILING_ROUTE = "/admin/profiling"
PROFILING_STOP_ROUTE = "/admin/profiling/stop"
# engine flight recorder (metrics listener): the live per-launch ring —
# device introspection counters, launch ids (join key for slow-query
# lines and typed batch errors), HBM/staleness accounting per built engine
FLIGHTREC_ROUTE = "/admin/flightrec"
# replica serving group (metrics listener): per-worker applied versions,
# pending counts, listener ports, and the hedge policy's live state
REPLICAS_ROUTE = "/admin/replicas"
# anti-entropy mirror scrubber (metrics listener, engine/scrub.py): GET
# reads counters/last-pass state, POST runs one full pass on demand and
# returns the per-nid report
SCRUB_ROUTE = "/admin/scrub"
# multi-daemon HA plane (metrics listener, api/follower.py): role,
# applied/observed leader versions, tail state, bootstrap/reconnect
# counters on a follower; store version + watch heartbeat on a leader
HA_ROUTE = "/admin/ha"
# workload observatory (metrics listener, observability_workload.py):
# hot-key sketch top-K + cache attribution, live SLO burn rates, and the
# capture/replay traffic profile `keto-tpu admin capture` downloads
HOTKEYS_ROUTE = "/admin/hotkeys"
SLO_ROUTE = "/admin/slo"
WORKLOAD_ROUTE = "/admin/workload"
SPEC_ROUTE = "/.well-known/openapi.json"

# route -> router kind, the ONE ownership table (consumed by the spec
# builder so a port's served spec can never advertise a route the port
# 404s; keep in sync with _resolve when adding routes)
ROUTE_KINDS = {
    READ_ROUTE_BASE: "read",
    CHECK_ROUTE_BASE: "read",
    CHECK_OPENAPI_ROUTE: "read",
    CHECK_BATCH_ROUTE: "read",
    EXPAND_ROUTE: "read",
    LIST_OBJECTS_ROUTE: "read",
    LIST_SUBJECTS_ROUTE: "read",
    FILTER_ROUTE: "read",
    WATCH_ROUTE: "read",
    WRITE_ROUTE_BASE: "write",
    ALIVE_PATH: "shared",
    READY_PATH: "shared",
    VERSION_PATH: "shared",
    SPEC_ROUTE: "shared",
    METRICS_PATH: "metrics",
    PROFILING_ROUTE: "metrics",
    PROFILING_STOP_ROUTE: "metrics",
    FLIGHTREC_ROUTE: "metrics",
    REPLICAS_ROUTE: "metrics",
    SCRUB_ROUTE: "metrics",
    HA_ROUTE: "metrics",
    HOTKEYS_ROUTE: "metrics",
    SLO_ROUTE: "metrics",
    WORKLOAD_ROUTE: "metrics",
}


def _get_page_size(params: dict[str, str], default: int) -> int:
    """page_size query param; malformed values are a 400, not a 500."""
    raw = params.get("page_size", "")
    if not raw:
        return default
    try:
        return int(raw) or default
    except ValueError:
        raise MalformedInputError(debug=f"invalid page_size {raw!r}")


def _get_max_depth(params: dict[str, str]) -> int:
    """ref: internal/x/max_depth.go (param name "max-depth", 0 if absent)."""
    raw = params.get("max-depth", "")
    if not raw:
        return 0
    try:
        return int(raw, 0)
    except ValueError:
        raise MalformedInputError(debug=f"invalid max-depth {raw!r}")


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "keto_tpu"

    # members injected by make_handler_class
    registry = None
    batcher = None
    worker = None  # replica ServeWorker (api/replica.py) | None
    kind = "read"  # read | write | metrics
    cors = None  # serve.<kind>.cors config dict (ref: daemon.go:289-349)
    watch_slots = None  # per-listener SSE watcher cap (make_handler_class)

    # -- plumbing -------------------------------------------------------------

    def log_message(self, fmt, *args):  # route through our logger, not stderr
        from ..observability import logger

        logger.debug("http %s", fmt % args)

    def _cors_headers(self) -> list[tuple[str, str]]:
        """CORS response headers for allowed origins (ref: negroni CORS
        middleware wired per listener, daemon.go:289-349)."""
        cfg = self.cors
        if not cfg or not cfg.get("enabled"):
            return []
        origin = self.headers.get("Origin")
        if not origin:
            return []
        allowed = cfg.get("allowed_origins") or ["*"]
        if "*" not in allowed and origin not in allowed:
            return []
        methods = cfg.get("allowed_methods") or [
            "GET", "POST", "PUT", "PATCH", "DELETE", "OPTIONS",
        ]
        headers = cfg.get("allowed_headers") or ["Authorization", "Content-Type"]
        return [
            (
                "Access-Control-Allow-Origin",
                "*" if "*" in allowed else origin,
            ),
            ("Access-Control-Allow-Methods", ", ".join(methods)),
            ("Access-Control-Allow-Headers", ", ".join(headers)),
            ("Vary", "Origin"),
        ]

    def _write(
        self, code: int, body: bytes, content_type="application/json",
        extra_headers: list[tuple[str, str]] | None = None,
    ) -> None:
        self._last_status = code
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers or ():
            self.send_header(k, v)
        for k, v in self._cors_headers():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _json(
        self, code: int, obj, location: str | None = None,
        extra_headers: list[tuple[str, str]] | None = None,
    ) -> None:
        body = json.dumps(obj).encode()
        self._last_status = code
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        if location is not None:
            self.send_header("Location", location)
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers or ():
            self.send_header(k, v)
        for k, v in self._cors_headers():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _error(self, err: Exception) -> None:
        if isinstance(err, KetoError):
            extra = None
            ra = getattr(err, "retry_after_s", None)
            if ra is not None:
                # shed responses (OverloadedError) carry the retry hint
                # the way HTTP specifies it; the gRPC planes mirror it as
                # trailing metadata from the same field
                from ..resilience import retry_after_header_value

                extra = [("Retry-After", retry_after_header_value(ra))]
            self._json(err.status, err.to_dict(), extra_headers=extra)
        else:
            e = KetoError(str(err))
            self._json(500, e.to_dict())

    def _params(self) -> dict[str, str]:
        qs = urllib.parse.urlparse(self.path).query
        return {k: v[0] for k, v in urllib.parse.parse_qs(qs).items()}

    def _body_json(self):
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        try:
            return json.loads(raw or b"null")
        except json.JSONDecodeError as e:
            raise MalformedInputError(f"could not unmarshal json: {e}")

    def _route(self, method: str) -> None:
        path = urllib.parse.urlparse(self.path).path.rstrip("/") or "/"
        metrics = self.registry.metrics()
        # metrics are labeled by the MATCHED route constant — never the raw
        # request path (arbitrary scanner URLs would create unbounded
        # Prometheus label cardinality); unmatched requests share one label
        resolved = self._resolve(method, path)
        label = f"{method} {resolved[0]}" if resolved else "unmatched"
        # W3C trace ingestion: a traceparent header joins the caller's
        # trace (as a child span); absence starts a fresh one. The
        # RequestTrace rides the contextvar so the batcher/engine layers
        # and the traced store ops correlate without signature threading.
        ctx = parse_traceparent(self.headers.get("traceparent"))
        rt = RequestTrace(ctx.child() if ctx is not None else None)
        self._rt = rt
        self._last_status = 200
        token = set_request_trace(rt)
        t0 = time.perf_counter()
        outcome = None
        try:
            with metrics.observe_request("http", label) as outcome:
                if resolved is None:
                    outcome["code"] = "404"
                    from ..errors import NotFoundError

                    self._json(404, NotFoundError("route not found").to_dict())
                    return
                try:
                    # span-per-request (ref: otelx.TraceHandler,
                    # daemon.go:131-133); root=True makes this span the
                    # request's trace ROOT — it takes rt.ctx's span id,
                    # so the batcher/engine/store spans parent-link to
                    # it and IT parent-links to the caller's client span
                    # from the ingested traceparent (the OTLP export
                    # plane's hierarchy)
                    with self.registry.tracer().span(
                        f"http.{label}", ctx=rt.ctx, root=True
                    ):
                        resolved[1]()
                    # handlers that WRITE an error status directly (503
                    # ready probe, 404 nil expand, 403 check mirror, 429
                    # watch cap) must not count as code="OK"
                    if self._last_status >= 400:
                        outcome["code"] = str(self._last_status)
                except KetoError as e:
                    outcome["code"] = str(e.status)
                    self._error(e)
                except (BrokenPipeError, ConnectionResetError):
                    raise
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    outcome["code"] = "500"
                    self._error(e)
        finally:
            reset_request_trace(token)
            # SSE watch streams block in the handler for their whole
            # lifetime by design — a stream's duration is not a slow
            # query, so it never trips the threshold log
            finish_request_telemetry(
                metrics,
                self.registry.config.get("log.slow_query_ms"),
                "http", label, rt,
                outcome.code if outcome is not None else "500",
                time.perf_counter() - t0,
                skip_slow=(
                    resolved is not None and resolved[0] == WATCH_ROUTE
                ),
                sample_rate=self.registry.config.get(
                    "log.request_sample_rate"
                ),
                workload=self.registry.workload_observatory(),
            )

    # -- routing --------------------------------------------------------------

    def _resolve(self, method: str, path: str):
        """(route constant, handler thunk) for a matched route, else None."""
        # shared routes
        if method == "GET":
            if path == ALIVE_PATH:
                return ALIVE_PATH, lambda: self._json(200, {"status": "ok"})
            if path == READY_PATH:

                def ready():
                    ok = self.registry.ready.is_set()
                    self._json(
                        200 if ok else 503,
                        {"status": "ok" if ok else "unavailable"},
                    )

                return READY_PATH, ready
            if path == VERSION_PATH:
                return VERSION_PATH, lambda: self._json(
                    200, {"version": self.registry.version}
                )
            if path == SPEC_ROUTE and self.kind in ("read", "write"):
                # generated-from-route-table OpenAPI document (ref serves
                # its swagger spec + docs, doc_swagger.go:1)
                def spec():
                    from .openapi import build_spec

                    self._json(
                        200, build_spec(self.registry.version, kind=self.kind)
                    )

                return SPEC_ROUTE, spec

        if self.kind == "metrics":
            if method == "GET" and path == METRICS_PATH:
                return METRICS_PATH, self._metrics_export
            if path == PROFILING_ROUTE:
                if method == "GET":
                    return PROFILING_ROUTE, self._profiling_status
                if method == "POST":
                    return PROFILING_ROUTE, self._profiling_start
            if method == "POST" and path == PROFILING_STOP_ROUTE:
                return PROFILING_STOP_ROUTE, self._profiling_stop
            if method == "GET" and path == FLIGHTREC_ROUTE:
                return FLIGHTREC_ROUTE, self._flightrec_dump
            if method == "GET" and path == REPLICAS_ROUTE:
                return REPLICAS_ROUTE, self._replicas_status
            if method == "GET" and path == HA_ROUTE:
                return HA_ROUTE, self._ha_status
            if path == SCRUB_ROUTE:
                if method == "GET":
                    return SCRUB_ROUTE, self._scrub_status
                if method == "POST":
                    return SCRUB_ROUTE, self._scrub_trigger
            if method == "GET" and path == HOTKEYS_ROUTE:
                return HOTKEYS_ROUTE, self._hotkeys_dump
            if method == "GET" and path == SLO_ROUTE:
                return SLO_ROUTE, self._slo_dump
            if method == "GET" and path == WORKLOAD_ROUTE:
                return WORKLOAD_ROUTE, self._workload_profile
            return None

        if self.kind == "read":
            if method == "GET" and path == READ_ROUTE_BASE:
                return READ_ROUTE_BASE, self._get_relations
            if path == CHECK_ROUTE_BASE and method in ("GET", "POST"):
                return CHECK_ROUTE_BASE, lambda: self._check(
                    method, mirror_status=True
                )
            if path == CHECK_OPENAPI_ROUTE and method in ("GET", "POST"):
                return CHECK_OPENAPI_ROUTE, lambda: self._check(
                    method, mirror_status=False
                )
            if path == CHECK_BATCH_ROUTE and method == "POST":
                return CHECK_BATCH_ROUTE, self._check_batch
            if method == "GET" and path == EXPAND_ROUTE:
                return EXPAND_ROUTE, self._expand
            if method == "GET" and path == LIST_OBJECTS_ROUTE:
                return LIST_OBJECTS_ROUTE, self._list_objects
            if method == "GET" and path == LIST_SUBJECTS_ROUTE:
                return LIST_SUBJECTS_ROUTE, self._list_subjects
            if method == "POST" and path == FILTER_ROUTE:
                return FILTER_ROUTE, self._filter
            if method == "GET" and path == WATCH_ROUTE:
                return WATCH_ROUTE, self._watch
            return None

        # write router
        if path == WRITE_ROUTE_BASE:
            if method == "PUT":
                return WRITE_ROUTE_BASE, self._create_relation
            if method == "DELETE":
                return WRITE_ROUTE_BASE, self._delete_relations
            if method == "PATCH":
                return WRITE_ROUTE_BASE, self._patch_relations
        return None

    # -- read handlers --------------------------------------------------------

    def _nid(self) -> str:
        """Per-request network id via the Contextualizer hook (ref:
        ketoctx/contextualizer.go:12-19); default: the registry nid."""
        return self.registry.nid_for(self.headers)

    def _get_relations(self) -> None:
        """ref: read_server.go:122-175."""
        params = self._params()
        query = RelationQuery.from_url_query(params)
        self.registry.validate_namespaces(query)
        page_size = int(params.get("page_size") or 0) or self.registry.config.page_size()
        tuples, next_token = self.registry.relation_tuple_manager().get_relation_tuples(
            query,
            page_token=params.get("page_token", ""),
            page_size=page_size,
            nid=self._nid(),
        )
        self._json(200, GetResponse(tuples, next_token).to_dict())

    def _enforce_snaptoken(self, token: str, nid: str) -> int:
        from ..engine.snaptoken import enforce_snaptoken

        return enforce_snaptoken(self.registry, token, nid)

    def _ingest_deadline(self):
        """The request's end-to-end Deadline from the
        `x-request-timeout-ms` header (or serve.check.default_deadline_ms,
        clamped to max_deadline_ms), attached to the RequestTrace so the
        cache -> batcher -> device pipeline fails fast at every stage
        boundary once the budget is spent. Returns the rt (or None)."""
        from ..resilience import ingest_deadline, parse_timeout_ms

        rt = getattr(self, "_rt", None)
        if rt is not None:
            rt.deadline = ingest_deadline(
                self.registry.config,
                request_ms=parse_timeout_ms(
                    self.headers.get("x-request-timeout-ms")
                ),
            )
        return rt

    def _check(self, method: str, mirror_status: bool) -> None:
        """ref: check/handler.go getCheck/postCheck + 403 mirroring.
        Snaptokens (keto_tpu extension; the reference REST check has no
        token surface at all): a `snaptoken` query param pins the read,
        and the response carries the evaluated version's token in the
        X-Keto-Snaptoken header — a header, so the parity JSON body
        stays byte-identical to the reference's {"allowed": ...}.

        `explain=true` (query param, or an `explain` body field on POST
        — keto_tpu extension, §5m) returns a DecisionTrace beside the
        verdict: answering tier + cause, host-re-walked witness path
        (differential-checked against the authoritative device
        verdict), exhaustion summary for DENY, per-stage ms, launch
        ids. Explain bypasses the check cache and is admission-bounded
        by the explain.max_per_s token bucket (typed 429)."""
        from ..engine.snaptoken import encode_snaptoken
        from ..resilience import admit_check, admit_explain

        # deadline ingestion + admission gate BEFORE any work — body
        # parsing included: a shed/draining POST must cost nothing (the
        # overload path is exactly what this gate protects). The explain
        # flag picks the gate: explain rides the token bucket, never the
        # batcher's queue accounting. The query param decides PRE-parse;
        # a POST that opts in via the body field instead pays one extra
        # advisory batcher check (state-free) and then the token gate.
        rt = self._ingest_deadline()
        params = self._params()
        explain = params.get("explain", "").lower() in ("1", "true")
        if explain:
            admit_explain(self.registry, rt)
        else:
            admit_check(self.registry, self.batcher, rt)
        body = None
        if method != "GET":
            body = self._body_json()
            if not isinstance(body, dict):
                raise MalformedInputError(
                    "could not unmarshal json: expected object"
                )
            if not explain and body.get("explain"):
                explain = True
                admit_explain(self.registry, rt)
        max_depth = _get_max_depth(params)
        if method == "GET":
            t = RelationTuple.from_url_query(params)
        else:
            t = RelationTuple.from_dict(body)
        nid = self._nid()
        token = params.get("snaptoken", "")
        if self.worker is not None:
            # replica mode: the snaptoken routing rule picks the
            # answering worker and the version the response token is
            # minted at (token parse/409 precedence matches the
            # single-stack enforce path: before the namespace corner)
            from .replica import resolve_version, serve_on

            target, version = resolve_version(
                self.worker.group, self.worker, nid, token, rt
            )
        else:
            target = None
            version = self._enforce_snaptoken(token, nid)
        token_hdr = [("X-Keto-Snaptoken", encode_snaptoken(version, nid))]
        try:
            self.registry.validate_namespaces(t)
        except NamespaceNotFoundError:
            # unknown namespace => allowed=false, not 404 (handler.go:156-161)
            rt.tier = "vocab"
            obs = self.registry.workload_observatory()
            if obs is not None:
                # the swallowed corner never reaches the serve gate, so
                # the workload accounting records it here
                obs.record_check(nid, t, False, tier="vocab")
            code = 403 if mirror_status else 200
            payload: dict = {"allowed": False}
            if explain:
                # the REST-only swallowed corner never reaches the
                # engine: the trace says so (vocab tier — the name is
                # outside the configured vocabulary)
                from ..engine.explain import vocab_trace

                self.registry.metrics().explain_requests_total.inc()
                payload["decision_trace"] = vocab_trace(
                    version, encode_snaptoken(version, nid),
                    "namespace_not_found",
                )
            self._json(code, payload, extra_headers=token_hdr)
            return
        if explain:
            from ..engine.explain import serve_explain

            res, trace = serve_explain(
                self.registry, nid, t, max_depth, version, rt
            )
            if res.error is not None:
                raise res.error
            code = 403 if (mirror_status and not res.allowed) else 200
            self._json(
                code, {"allowed": res.allowed, "decision_trace": trace},
                extra_headers=token_hdr,
            )
            return
        if target is not None:
            res = serve_on(target, nid, t, max_depth, version, rt)
        else:
            # serve fast path (api/check_cache.py): a hit returns before
            # the batcher — no assemble/dispatch/device stages run, and
            # the response (snaptoken included) is byte-identical to a
            # miss at the same store version
            from .check_cache import cached_check

            res = cached_check(
                self.registry, self.batcher, nid, t, max_depth, version,
                rt,
            )
        if res.error is not None:
            raise res.error
        code = 403 if (mirror_status and not res.allowed) else 200
        self._json(code, {"allowed": res.allowed}, extra_headers=token_hdr)

    def _check_batch(self) -> None:
        """keto_tpu extension: POST {"tuples": [...], "max_depth"?} (or a
        bare array) -> {"results": [{"allowed": bool} | {"allowed":
        false, "error": str}, ...]} in request order. The whole batch
        rides ONE engine.check_batch launch; per-item problems (bad
        subject, unknown names via host replay) never fail the batch."""
        from ..resilience import admit_check

        # draining/expired gate (no queue bound: the batch rides one
        # direct engine launch, not the batcher queue)
        admit_check(self.registry, None, self._ingest_deadline())
        params = self._params()
        body = self._body_json()
        if isinstance(body, dict):
            raw = body.get("tuples")
            raw_depth = body.get("max_depth")
            if raw_depth is None:
                # ABSENCE, not falsiness: an explicit JSON max_depth of 0
                # must override a non-zero ?max-depth query param
                max_depth = _get_max_depth(params)
            else:
                try:
                    max_depth = int(raw_depth)
                except (TypeError, ValueError):
                    raise MalformedInputError("max_depth must be an integer")
        else:
            raw = body
            max_depth = _get_max_depth(params)
        if not isinstance(raw, list):
            raise MalformedInputError(
                "could not unmarshal json: expected array of relation tuples"
            )
        from ..engine.snaptoken import encode_snaptoken

        nid = self._nid()
        req_token = params.get("snaptoken", "")
        if isinstance(body, dict):
            req_token = body.get("snaptoken") or req_token
        version = self._enforce_snaptoken(req_token, nid)
        idx: list[int] = []
        tuples: list[RelationTuple] = []
        out: list[dict] = [None] * len(raw)  # type: ignore[list-item]
        for i, d in enumerate(raw):
            try:
                if not isinstance(d, dict):
                    raise MalformedInputError(
                        "could not unmarshal json: expected object"
                    )
                t = RelationTuple.from_dict(d)
                # unlike the single-check REST route (which swallows
                # unknown namespaces to allowed=false for parity), the
                # batch extension reports them per item — strictly more
                # information, and consistent with the gRPC batch plane
                self.registry.validate_namespaces(t)
            except KetoError as e:
                out[i] = {"allowed": False, "error": e.message}
                continue
            idx.append(i)
            tuples.append(t)
        engine = self.registry.check_engine(nid)
        obs = self.registry.workload_observatory()
        for pos, (i, res) in enumerate(
            zip(idx, engine.check_batch(tuples, max_depth))
        ):
            if res.error is not None:
                out[i] = {"allowed": False, "error": str(res.error)}
            else:
                out[i] = {"allowed": res.allowed}
                if obs is not None:
                    # per-item workload accounting (the batch bypasses
                    # the single-check serve gate); the whole batch rode
                    # one launch, so no per-item tier stamp exists here
                    obs.record_check(nid, tuples[pos], res.allowed)
        self._json(
            200,
            {"results": out, "snaptoken": encode_snaptoken(version, nid)},
        )

    def _expand(self) -> None:
        """ref: expand/handler.go:43-107 (GET, subject-set params)."""
        params = self._params()
        max_depth = _get_max_depth(params)
        try:
            subject_set = SubjectSet(
                namespace=params["namespace"],
                object=params["object"],
                relation=params["relation"],
            )
        except KeyError:
            raise MalformedInputError(
                debug="expand requires namespace, object, and relation"
            )
        self.registry.validate_namespaces(subject_set)
        tree = self.registry.expand_engine(self._nid()).expand(subject_set, max_depth)
        if tree is None:
            from ..errors import NotFoundError

            self._json(404, NotFoundError("no relation tuples found").to_dict())
            return
        self._json(200, tree.to_dict())

    def _list_objects(self) -> None:
        """keto_tpu reverse-reachability extension: GET with namespace,
        relation, and a subject (subject_id or subject_set.*) -> the
        sorted objects the subject reaches, paginated; snaptoken-
        enforced like check, evaluated-version token in the
        X-Keto-Snaptoken header."""
        from ..engine.snaptoken import encode_snaptoken

        params = self._params()
        max_depth = _get_max_depth(params)
        namespace = params.get("namespace")
        relation = params.get("relation")
        if not namespace or not relation:
            raise MalformedInputError(
                debug="list-objects requires namespace and relation"
            )
        subject = self._subject_from_params(params)
        nid = self._nid()
        version = self._enforce_snaptoken(params.get("snaptoken", ""), nid)
        self.registry.validate_namespaces(
            RelationQuery(namespace=namespace),
            subject if isinstance(subject, SubjectSet) else None,
        )
        page_size = _get_page_size(params, self.registry.config.page_size())
        engine = self.registry.check_engine(nid)
        objects, next_token = engine.list_objects(
            namespace, relation, subject, max_depth,
            page_size=page_size, page_token=params.get("page_token", ""),
        )
        self._json(
            200,
            {"objects": objects, "next_page_token": next_token},
            extra_headers=[("X-Keto-Snaptoken", encode_snaptoken(version, nid))],
        )

    def _list_subjects(self) -> None:
        """keto_tpu reverse-reachability extension: GET with namespace,
        object, relation -> the sorted plain subject ids that reach the
        node, paginated."""
        from ..engine.snaptoken import encode_snaptoken

        params = self._params()
        max_depth = _get_max_depth(params)
        try:
            namespace = params["namespace"]
            obj = params["object"]
            relation = params["relation"]
        except KeyError:
            raise MalformedInputError(
                debug="list-subjects requires namespace, object, and relation"
            )
        nid = self._nid()
        version = self._enforce_snaptoken(params.get("snaptoken", ""), nid)
        self.registry.validate_namespaces(RelationQuery(namespace=namespace))
        page_size = _get_page_size(params, self.registry.config.page_size())
        engine = self.registry.check_engine(nid)
        subjects, next_token = engine.list_subjects(
            namespace, obj, relation, max_depth,
            page_size=page_size, page_token=params.get("page_token", ""),
        )
        self._json(
            200,
            {"subject_ids": subjects, "next_page_token": next_token},
            extra_headers=[("X-Keto-Snaptoken", encode_snaptoken(version, nid))],
        )

    def _filter(self) -> None:
        """keto_tpu bulk-ACL-filter extension: POST {"namespace",
        "relation", "subject_id" | "subject_set", "objects": [...],
        "max_depth"?, "snaptoken"?} -> {"allowed_objects": [...],
        "snaptoken": ...} — the subset of the candidate column the
        subject can see, in request order. Admission (draining 429 /
        expired 504 / filter.max_objects 400) runs BEFORE any work; the
        engine re-checks the deadline at every chunk boundary; replica
        mode routes the snaptoken through the hold/route/escalate rule
        like Check."""
        from ..engine.snaptoken import encode_snaptoken
        from ..ketoapi import _subject_fields_from_dict
        from ..resilience import admit_filter

        rt = self._ingest_deadline()
        body = self._body_json()
        if not isinstance(body, dict):
            raise MalformedInputError("could not unmarshal json: expected object")
        objects = body.get("objects")
        if not isinstance(objects, list) or not all(
            isinstance(o, str) for o in objects
        ):
            raise MalformedInputError(
                "filter requires \"objects\": an array of object names"
            )
        admit_filter(self.registry, len(objects), rt)
        namespace = body.get("namespace")
        relation = body.get("relation")
        if not namespace or not relation:
            raise MalformedInputError(
                debug="filter requires namespace and relation"
            )
        subject_id, subject_set = _subject_fields_from_dict(body)
        if subject_id is None and subject_set is None:
            from ..errors import NilSubjectError

            raise NilSubjectError()
        subject = subject_set if subject_set is not None else subject_id
        raw_depth = body.get("max_depth")
        if raw_depth is None:
            max_depth = _get_max_depth(self._params())
        else:
            try:
                max_depth = int(raw_depth)
            except (TypeError, ValueError):
                raise MalformedInputError("max_depth must be an integer")
        nid = self._nid()
        token = body.get("snaptoken") or self._params().get("snaptoken", "")
        if self.worker is not None:
            from .replica import resolve_version

            _target, version = resolve_version(
                self.worker.group, self.worker, nid, token, rt
            )
        else:
            version = self._enforce_snaptoken(token, nid)
        self.registry.validate_namespaces(
            RelationQuery(namespace=namespace),
            subject if isinstance(subject, SubjectSet) else None,
        )
        engine = self.registry.check_engine(nid)
        allowed = engine.filter_objects(
            namespace, relation, subject, objects, max_depth,
            deadline=getattr(rt, "deadline", None) if rt is not None else None,
        )
        self._json(
            200,
            {
                "allowed_objects": allowed,
                "snaptoken": encode_snaptoken(version, nid),
            },
        )

    # SSE keep-alive cadence: also the disconnect-detection bound (a
    # vanished client is only noticed on the next write). Default for
    # the `watch.heartbeat_s` schema key — a half-open TCP connection
    # (NAT drop, killed peer) is detected within one heartbeat, the
    # write fails, and the finally below frees the subscriber ring
    # instead of letting an orphaned cursor pin changelog retention.
    WATCH_HEARTBEAT_S = 5.0

    def _watch(self) -> None:
        """keto_tpu watch extension: the streaming changelog as
        Server-Sent Events. `snaptoken` resumes the cursor (every change
        strictly after it, exactly once, in version order — 409 when the
        token is ahead of the store, an explicit `reset` event when the
        bounded changelog no longer reaches it); `namespace` filters;
        `max_events` (scripting/testing aid) closes the stream after N
        events. Each SSE message is one committed store version:

            event: change | reset
            data: {"event_type", "snaptoken", "changes": [
                      {"action": "insert"|"delete", "relation_tuple": {...}}]}

        Token/parse errors surface as normal JSON errors (they happen
        before the stream opens)."""
        from ..engine.snaptoken import parse_snaptoken

        params = self._params()
        nid = self._nid()
        namespace = params.get("namespace", "")
        if namespace:
            self.registry.validate_namespaces(RelationQuery(namespace=namespace))
        max_events = None
        if params.get("max_events"):
            try:
                max_events = int(params["max_events"])
            except ValueError:
                raise MalformedInputError(
                    debug=f"invalid max_events {params['max_events']!r}"
                )
        min_version = parse_snaptoken(params.get("snaptoken", ""), nid)
        # SSE streams pin one server thread each, exactly like gRPC
        # watch streams pin a worker. The CONFIG KNOB is shared
        # (serve.read.grpc.max_watchers) but the slot pool is
        # per-listener: each transport serves from its own thread pool,
        # so the process-wide ceiling is the knob times the number of
        # watch-capable listeners
        if not self.watch_slots.acquire(blocking=False):
            self._json(
                429,
                {"error": {"code": 429, "status": "Too Many Requests",
                           "message": "too many concurrent watchers"}},
            )
            return
        try:
            self._watch_stream(nid, namespace, min_version, max_events)
        finally:
            self.watch_slots.release()

    def _watch_stream(self, nid, namespace, min_version, max_events) -> None:
        sub = self.registry.watch_hub().subscribe(nid, min_version)
        self.close_connection = True  # the stream IS the response body
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            for k, v in self._cors_headers():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(b": stream open\n\n")
            self.wfile.flush()
            heartbeat_s = float(
                self.registry.config.get(
                    "watch.heartbeat_s", self.WATCH_HEARTBEAT_S
                )
            )
            delivered = 0
            last_write = time.monotonic()
            while max_events is None or delivered < max_events:
                # keep-alives are due by WALL time, not idle-gets: a
                # stream whose events are all namespace-filtered out is
                # busy and would otherwise stay wire-silent forever
                if time.monotonic() - last_write >= heartbeat_s:
                    last_write = time.monotonic()
                    self.wfile.write(b": keep-alive\n\n")
                    self.wfile.flush()
                event = sub.get(
                    timeout=max(
                        0.05,
                        heartbeat_s - (time.monotonic() - last_write),
                    )
                )
                if event is None:
                    if sub.closed:  # daemon drain ends the stream
                        break
                    continue
                event = event.filtered(namespace)
                if event is None:
                    continue
                payload = json.dumps(event.to_dict())
                self.wfile.write(
                    f"event: {event.kind}\ndata: {payload}\n\n".encode()
                )
                self.wfile.flush()
                last_write = time.monotonic()
                delivered += 1
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away: normal end of a watch stream
        finally:
            sub.close()

    @staticmethod
    def _subject_from_params(params: dict[str, str]):
        """subject_id or subject_set.{namespace,object,relation} from URL
        params (the check route's subject vocabulary)."""
        if "subject_id" in params:
            return params["subject_id"]
        try:
            return SubjectSet(
                namespace=params["subject_set.namespace"],
                object=params["subject_set.object"],
                relation=params["subject_set.relation"],
            )
        except KeyError:
            raise MalformedInputError(
                debug="a subject_id or subject_set.* subject is required"
            )

    # -- profiling admin (metrics listener) -----------------------------------

    def _profiling_status(self) -> None:
        self._json(200, self.registry.profiler().status())

    @staticmethod
    def _confine_profile_path(path: str) -> str:
        """Client-supplied artifact paths resolve INSIDE the profile
        directory (KETO_PROFILE_DIR, default the system tempdir) — the
        admin endpoint must not be an arbitrary-file-write primitive for
        whoever can reach the metrics port."""
        import os
        import tempfile

        base = os.path.realpath(
            os.environ.get("KETO_PROFILE_DIR") or tempfile.gettempdir()
        )
        resolved = os.path.realpath(os.path.join(base, path))
        if resolved != base and not resolved.startswith(base + os.sep):
            raise MalformedInputError(
                debug=f"profiling path must stay inside {base!r} "
                "(set KETO_PROFILE_DIR to change the allowed directory)"
            )
        return resolved

    def _profiling_start(self) -> None:
        """POST /admin/profiling {"mode": "cpu"|"mem"|"jax", "path"?}
        (or ?mode= query param): start an on-demand capture against the
        RUNNING serve. 400 on unknown mode or a path escaping the
        profile directory, 409 while one is running."""
        body = self._body_json()
        params = self._params()
        mode = ""
        path = None
        if isinstance(body, dict):
            mode = body.get("mode") or ""
            path = body.get("path") or None
        mode = mode or params.get("mode", "")
        path = path or params.get("path") or None
        if path is not None:
            path = self._confine_profile_path(path)
        try:
            self._json(200, self.registry.profiler().start(mode, path))
        except ValueError as e:
            raise MalformedInputError(debug=str(e))
        except RuntimeError as e:
            self._json(
                409,
                {"error": {"code": 409, "status": "Conflict",
                           "message": str(e)}},
            )

    def _profiling_stop(self) -> None:
        """POST /admin/profiling/stop: end the capture and write its
        artifact. Idempotent — a stop with nothing running answers
        {"running": false, "artifact": null} instead of erroring."""
        artifact = self.registry.profiler().stop()
        self._json(200, {"running": False, "artifact": artifact})

    def _metrics_export(self) -> None:
        """GET /metrics/prometheus: classic text exposition by default;
        an Accept header asking for `application/openmetrics-text` gets
        the OpenMetrics format instead — the one that carries the
        EXEMPLARS (trace_id per stage-histogram bucket) linking the
        metrics plane to the trace plane."""
        metrics = self.registry.metrics()
        accept = self.headers.get("Accept") or ""
        if "application/openmetrics-text" in accept:
            self._write(
                200, metrics.export_openmetrics(),
                content_type=metrics.OPENMETRICS_CONTENT_TYPE,
            )
            return
        self._write(
            200, metrics.export(),
            content_type="text/plain; version=0.0.4; charset=utf-8",
        )

    def _flightrec_dump(self) -> None:
        """GET /admin/flightrec: the live launch ring plus
        per-built-engine HBM/staleness snapshots. Entries come back in
        LAUNCH-ID order (newest last): the ring itself holds resolve
        order, and with two batching planes sharing one engine a later
        submit can resolve first — id order is the submission order
        consumers join on. Entry launch_ids join the slow-query WARNING
        lines, the request log, and typed CheckBatchFailedError
        messages; entry ages are derivable from `now_mono` - entry
        `t_mono` (monotonic stamps — wall clocks are banned repo-wide).
        Reads only already-built state: no engine or device mirror is
        instantiated from the admin plane.

        Filters (the ring now holds 7 launch kinds — dumping everything
        to find one filter launch is noise): `?kind=` keeps entries of
        one launch kind (check | closure | expand | list_objects |
        list_subjects | filter | filter_closure), `?trace_id=` keeps
        entries whose riders carried that trace id, `?since_launch_id=`
        keeps entries with a STRICTLY larger launch id — the tail
        cursor: a poller passes the max id it has seen and downloads
        only the increment instead of the whole ring (id order is the
        documented join order, so the cursor is total). All compose."""
        import time as _time

        params = self._params()
        fr = self.registry.flight_recorder()
        hbm = {}
        for nid, engine in self.registry.built_engines().items():
            snap = getattr(engine, "hbm_snapshot", None)
            if snap is not None:
                hbm[nid] = snap()
        entries = sorted(
            fr.entries(), key=lambda e: e.get("launch_id") or 0
        )
        kind = params.get("kind", "")
        if kind:
            entries = [e for e in entries if e.get("kind") == kind]
        trace_id = params.get("trace_id", "")
        if trace_id:
            entries = [
                e for e in entries
                if trace_id in (e.get("trace_ids") or ())
            ]
        since = params.get("since_launch_id", "")
        if since:
            try:
                since_id = int(since)
            except ValueError:
                raise MalformedInputError(
                    "since_launch_id must be an integer"
                )
            entries = [
                e for e in entries
                if (e.get("launch_id") or 0) > since_id
            ]
        self._json(200, {
            "enabled": fr.enabled,
            "capacity": fr.capacity,
            "now_mono": _time.monotonic(),
            "entries": entries,
            "hbm": hbm,
        })

    def _scrub_status(self) -> None:
        """GET /admin/scrub: the anti-entropy scrubber's config +
        counters + last-pass facts (engine/scrub.py). Reads state only —
        no pass runs, no engine is built."""
        self._json(200, self.registry.mirror_scrubber().status())

    def _scrub_trigger(self) -> None:
        """POST /admin/scrub: run ONE full scrub pass NOW (works with
        `scrub.enabled: false` — the on-demand audit an operator runs
        after a device scare) and return the per-nid report plus the
        refreshed status."""
        scrubber = self.registry.mirror_scrubber()
        report = scrubber.scrub_pass()
        self._json(200, {"report": report, **scrubber.status()})

    def _replicas_status(self) -> None:
        """GET /admin/replicas: the replica serving group's live state —
        per-worker applied store versions (the snaptoken routing rule's
        input), admitted-but-unresolved counts, listener ports, and the
        hedge policy's current quantile delay. {"workers": []} outside
        replica mode (serve.check.workers unset or 1)."""
        group = self.registry.replica_group
        if group is None:
            self._json(200, {"workers": [], "group_pending": 0})
            return
        self._json(200, group.stats())

    def _ha_status(self) -> None:
        """GET /admin/ha: this daemon's HA-plane view. On a follower
        (follower.enabled): role, leader address, tail state, applied vs
        observed leader version (the per-daemon staleness the router's
        snaptoken rule keys on), last-frame age, and the bootstrap /
        reconnect counters the HA smoke pins (zero full reads in steady
        state). On a leader: role + live store version + watch
        heartbeat config — the ground truth followers converge to."""
        self._json(200, self.registry.ha_status())

    def _hotkeys_dump(self) -> None:
        """GET /admin/hotkeys: the Space-Saving sketches' live top-K
        (object keys, subject keys, full check tuples) with counts,
        overestimation errors, and traffic shares — plus the check-cache
        attribution join ("the top 100 keys are X% of traffic, hit-ratio
        Y" in one response). `?top=` bounds the per-kind entry count
        (default 100, capped at the sketch capacity by construction)."""
        params = self._params()
        top = 100
        raw = params.get("top", "")
        if raw:
            try:
                top = max(1, int(raw))
            except ValueError:
                raise MalformedInputError("top must be an integer")
        obs = self.registry.workload_observatory()
        cache = self.registry.check_cache()
        self._json(200, obs.hotkeys(
            top=top,
            cache_stats=cache.stats() if cache is not None else None,
        ))

    def _slo_dump(self) -> None:
        """GET /admin/slo: live burn rates per objective over both
        windows, event/bad counts, and the fast-burn flags — the same
        numbers the keto_tpu_slo_* gauges export, with the window
        arithmetic visible."""
        self._json(200, self.registry.workload_observatory().slo_status())

    def _workload_profile(self) -> None:
        """GET /admin/workload: the capture/replay traffic profile
        (key-popularity histograms, per-(nid, namespace, relation)
        accounting, read/write ratio) — `keto-tpu admin capture`
        downloads this and `tools/load_gen.py --profile` replays its
        shape. `?top=` bounds the key-popularity histogram length."""
        params = self._params()
        top = 100
        raw = params.get("top", "")
        if raw:
            try:
                top = max(1, int(raw))
            except ValueError:
                raise MalformedInputError("top must be an integer")
        self._json(200, self.registry.workload_observatory().profile(top=top))

    # -- write handlers -------------------------------------------------------

    def _create_relation(self) -> None:
        """ref: transact_server.go:105-133 (201 + Location + echo)."""
        body = self._body_json()
        if not isinstance(body, dict):
            raise MalformedInputError("could not unmarshal json: expected object")
        t = RelationTuple.from_dict(body)
        self.registry.validate_namespaces(t)
        from ..engine.snaptoken import encode_snaptoken

        nid = self._nid()
        manager = self.registry.relation_tuple_manager()
        manager.write_relation_tuples([t], nid=nid)
        location = READ_ROUTE_BASE + "?" + urllib.parse.urlencode(t.to_url_query())
        # post-write token in a header: the parity body stays the echoed
        # tuple exactly as the reference returns it
        self._json(
            201, t.to_dict(), location=location,
            extra_headers=[(
                "X-Keto-Snaptoken",
                encode_snaptoken(manager.version(nid=nid), nid),
            )],
        )

    def _delete_relations(self) -> None:
        """ref: transact_server.go:152-181 (by URL query, 204)."""
        query = RelationQuery.from_url_query(self._params())
        self.registry.validate_namespaces(query)
        self.registry.relation_tuple_manager().delete_all_relation_tuples(
            query, nid=self._nid()
        )
        self._write(204, b"", content_type="application/json")

    def _patch_relations(self) -> None:
        """ref: transact_server.go:211-252 (deltas, 204)."""
        body = self._body_json()
        if not isinstance(body, list):
            raise MalformedInputError("could not unmarshal json: expected array")
        deltas = [PatchDelta.from_dict(d) for d in body]
        inserts = [d.relation_tuple for d in deltas if d.action.value == "insert"]
        deletes = [d.relation_tuple for d in deltas if d.action.value == "delete"]
        self.registry.validate_namespaces(*inserts, *deletes)
        from ..engine.snaptoken import encode_snaptoken

        nid = self._nid()
        manager = self.registry.relation_tuple_manager()
        manager.transact_relation_tuples(inserts, deletes, nid=nid)
        # 204 has no body to carry a token; the header does (the parity
        # status/body stay exactly the reference's)
        self._write(
            204, b"",
            extra_headers=[(
                "X-Keto-Snaptoken",
                encode_snaptoken(manager.version(nid=nid), nid),
            )],
        )

    # -- HTTP verbs -----------------------------------------------------------

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_PUT(self):
        self._route("PUT")

    def do_DELETE(self):
        self._route("DELETE")

    def do_PATCH(self):
        self._route("PATCH")

    def do_OPTIONS(self):
        # CORS preflight: 204 with the allow headers (no routing)
        self.send_response(204)
        for k, v in self._cors_headers():
            self.send_header(k, v)
        self.send_header("Content-Length", "0")
        self.end_headers()


def make_handler_class(registry, kind: str, batcher=None, cors=None,
                       worker=None):
    # one watcher-slot pool per listener, shared by every connection of
    # the handler class (the SSE analog of _Services._watch_slots)
    watch_slots = threading.BoundedSemaphore(
        int(registry.config.get("serve.read.grpc.max_watchers", 16))
    )
    return type(
        f"KetoHTTP{kind.capitalize()}Handler",
        (_Handler,),
        {"registry": registry, "kind": kind, "batcher": batcher,
         "cors": cors, "watch_slots": watch_slots, "worker": worker},
    )


class RESTServer:
    """One HTTP listener (read, write, or metrics router)."""

    def __init__(
        self, registry, kind: str, host: str, port: int, batcher=None,
        cors=None, worker=None,
    ):
        handler = make_handler_class(registry, kind, batcher, cors=cors,
                                     worker=worker)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.kind = kind
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            name=f"keto-http-{self.kind}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
