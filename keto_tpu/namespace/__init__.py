from .ast import (
    ComputedSubjectSet,
    InvertResult,
    Operator,
    Relation,
    RelationType,
    SubjectSetRewrite,
    TupleToSubjectSet,
)
from .definitions import Namespace

__all__ = [
    "Namespace",
    "Relation",
    "RelationType",
    "SubjectSetRewrite",
    "ComputedSubjectSet",
    "TupleToSubjectSet",
    "InvertResult",
    "Operator",
]
