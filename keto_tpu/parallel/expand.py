"""SPMD multi-chip expand kernel: shard_map over the 1-D device mesh.

Same BFS-subgraph-gather semantics as the single-chip expand kernel
(engine/expand_kernel.py) with the full-edge CSR sharded by object slot
(the check tables' partition, parallel/sharding.build_sharded_full_csr)
and three collectives:

  - `psum` of per-task row lengths each step (a row lives on exactly one
    shard, so summing the per-shard lengths yields the global count —
    every shard then derives the IDENTICAL edge-buffer allocation)
  - `all_gather` of per-shard candidate children before the shared
    dedupe (as in the check kernel)
  - ONE `psum` of the edge buffers after the loop: each buffer slot is
    written by exactly the owning shard (values carried +1 so the empty
    sentinel stays EMPTY = sum(0s) - 1), so the merge is a single
    all-reduce instead of per-step traffic

The frontier, per-query counters, and needs_host masks stay replicated —
every device runs the identical merged state, so the while_loop trip
count agrees across the mesh.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# shard_map with the check_vma/check_rep compat shim (see parallel/kernel)
from .kernel import _shard_map

from ..engine.delta import DIRTY_FOR_EXPAND
from ..engine.expand_kernel import _ExpandState
from ..engine.kernel import (
    Expansion,
    _pair_key_probe,
    bounded_loop,
    dedupe_phase,
    dirty_lookup,
    empty_launch_stats,
    update_launch_stats,
)
from ..engine.snapshot import EMPTY
from .sharding import _EXPAND_SHARDED_KEYS

_kernel_cache: dict = {}
_kernel_cache_lock = threading.Lock()
_KERNEL_CACHE_CAP = 8


def _build_kernel(mesh: Mesh, axis: str, statics: tuple):
    fh_probes, max_steps, frontier_cap, edge_cap = statics
    F = frontier_cap
    E = edge_cap

    def run(shard_tabs, rep_tabs, q_obj, q_rel, q_depth, q_valid):
        tables = {k: v[0] for k, v in shard_tabs.items()}
        tables.update(rep_tabs)
        B = q_obj.shape[0]
        n_edges = tables["f_skind"].shape[0]
        n_rows = tables["f_row_ptr"].shape[0] - 1

        def row_span(row):
            row_c = jnp.clip(row, 0, n_rows)
            start = tables["f_row_ptr"][row_c]
            end = tables["f_row_ptr"][jnp.minimum(row_c + 1, n_rows)]
            start = jnp.where(row == EMPTY, 0, start)
            length = jnp.where(row == EMPTY, 0, end - start)
            return start, length

        def row_lookup(obj, rel):
            return _pair_key_probe(tables, "fh", obj, rel, fh_probes)

        root_row = row_lookup(q_obj, q_rel)
        _, root_len_local = row_span(root_row)
        root_len = jax.lax.psum(root_len_local, axis)
        root_has_children = (root_len > 0) & q_valid

        # dirty roots: replicated delta tables, identical per shard
        init_needs_host = q_valid & (
            (dirty_lookup(tables, q_obj, q_rel) & DIRTY_FOR_EXPAND) != 0
        )

        def step_fn(st: _ExpandState) -> _ExpandState:
            idx = jnp.arange(F, dtype=jnp.int32)
            live = (idx < st.n_tasks) & ~st.needs_host[st.t_q]
            q, obj, rel, depth = st.t_q, st.t_obj, st.t_rel, st.t_depth

            row = row_lookup(obj, rel)
            start, length_local = row_span(row)
            owned = length_local > 0  # the owner shard (or an empty row)
            # global row length: exactly one shard contributes
            length = jax.lax.psum(length_local, axis)
            emit = live & (depth >= 2)
            task_dirty = emit & (
                (dirty_lookup(tables, obj, rel) & DIRTY_FOR_EXPAND) != 0
            )
            needs_host_d = st.needs_host.at[q].max(task_dirty)
            emit = emit & ~task_dirty
            counts = jnp.where(emit, length, 0)  # REPLICATED

            # per-query bump allocation over the replicated counts: every
            # shard computes the identical global slot assignment
            order = jnp.argsort(q + jnp.where(live, 0, B))
            sq = q[order]
            scounts = counts[order]
            cum = jnp.cumsum(scounts) - scounts
            seg_first = jnp.concatenate(
                [jnp.ones(1, dtype=bool), sq[1:] != sq[:-1]]
            )
            seg_base = jnp.where(seg_first, cum, 0)
            seg_base = jax.lax.associative_scan(jnp.maximum, seg_base)
            within_q = cum - seg_base
            alloc = st.eb_count[sq] + within_q
            inv = jnp.zeros(F, dtype=jnp.int32).at[order].set(
                jnp.arange(F, dtype=jnp.int32)
            )
            alloc_t = alloc[inv]

            overflow = emit & ((alloc_t + counts) > E)
            needs_host = needs_host_d.at[q].max(overflow)
            emit = emit & ~overflow

            # segmented emission work list over the GLOBAL offsets; only
            # the owning shard writes content for its rows
            flat_counts = jnp.where(emit, counts, 0)
            offsets = jnp.cumsum(flat_counts) - flat_counts
            total = offsets[-1] + flat_counts[-1]
            j = jnp.arange(F * 4, dtype=jnp.int32)
            seg = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32) - 1
            seg = jnp.clip(seg, 0, F - 1)
            within = j - offsets[seg]
            in_range = j < jnp.minimum(total, F * 4)
            local = owned[seg]  # this shard owns the row's content
            e = jnp.clip(start[seg] + within, 0, max(n_edges - 1, 0))
            if n_edges:
                c_skind = tables["f_skind"][e]
                c_sa = tables["f_sa"][e]
                c_sb = tables["f_sb"][e]
            else:
                c_skind = jnp.zeros(F * 4, jnp.int32)
                c_sa = jnp.zeros(F * 4, jnp.int32)
                c_sb = jnp.zeros(F * 4, jnp.int32)

            dest_q = q[seg]
            write = in_range & emit[seg]
            dest = jnp.where(
                write & local, dest_q * E + alloc_t[seg] + within, B * E
            )
            # +1-carried values: the final cross-shard psum restores them
            # (slots default 0; exactly one shard writes each slot)
            eb_pobj = st.eb_pobj.at[dest].set(obj[seg] + 1, mode="drop")
            eb_prel = st.eb_prel.at[dest].set(rel[seg] + 1, mode="drop")
            eb_skind = st.eb_skind.at[dest].set(c_skind + 1, mode="drop")
            eb_sa = st.eb_sa.at[dest].set(c_sa + 1, mode="drop")
            eb_sb = st.eb_sb.at[dest].set(c_sb + 1, mode="drop")
            # replicated count update (derived from replicated values)
            eb_count = st.eb_count.at[dest_q].add(
                jnp.where(write, 1, 0), mode="drop"
            )
            trunc = (offsets + flat_counts) > F * 4
            needs_host = needs_host.at[q].max(emit & trunc)

            # next frontier: local subject-set children -> all_gather
            child_depth = depth[seg] - 1
            cand_valid = (
                write & local & (c_skind == 1) & (child_depth >= 2)
            )
            children_local = Expansion(
                q=dest_q, ctx=dest_q, obj=c_sa, rel=c_sb,
                depth=child_depth, valid=cand_valid,
            )
            gathered = Expansion(
                *(
                    jax.lax.all_gather(part, axis).reshape(-1)
                    for part in children_local
                )
            )
            nt_q, _nt_ctx, nt_obj, nt_rel, nt_depth, n_new, overflow_q = (
                dedupe_phase(gathered, F, B)
            )
            # dedupe reports int32 cause codes (shared with the check
            # kernel); the expand state keeps a boolean flag
            needs_host = needs_host | (overflow_q > 0)
            # launch counters: `write` and the dedupe output are derived
            # from REPLICATED values, so the stats vector stays identical
            # on every shard (sound under the replicated out_spec)
            stats = update_launch_stats(
                st.stats,
                st.n_tasks,
                (live & (depth >= 0)).sum(),
                jnp.int32(0),
                write.sum(),
                n_new,
            )
            return _ExpandState(
                nt_q, nt_obj, nt_rel, nt_depth, n_new,
                eb_pobj, eb_prel, eb_skind, eb_sa, eb_sb,
                eb_count, needs_host, st.step + 1, stats,
            )

        pad = F - B
        init = _ExpandState(
            t_q=jnp.pad(jnp.arange(B, dtype=jnp.int32), (0, pad)),
            t_obj=jnp.pad(q_obj.astype(jnp.int32), (0, pad)),
            t_rel=jnp.pad(q_rel.astype(jnp.int32), (0, pad)),
            t_depth=jnp.where(
                jnp.pad(q_valid, (0, pad), constant_values=False),
                jnp.pad(q_depth.astype(jnp.int32), (0, pad)),
                -1,
            ),
            n_tasks=jnp.int32(B),
            eb_pobj=jnp.zeros(B * E, jnp.int32),
            eb_prel=jnp.zeros(B * E, jnp.int32),
            eb_skind=jnp.zeros(B * E, jnp.int32),
            eb_sa=jnp.zeros(B * E, jnp.int32),
            eb_sb=jnp.zeros(B * E, jnp.int32),
            eb_count=jnp.zeros(B, jnp.int32),
            needs_host=init_needs_host,
            step=jnp.int32(0),
            stats=empty_launch_stats(),
        )

        def cond_fn(st: _ExpandState):
            return (st.step < max_steps) & (st.n_tasks > 0)

        # loop construct per backend (engine/kernel.bounded_loop); the
        # predicate is replicated, so all shards branch together and
        # step_fn's collectives stay aligned either way
        final = bounded_loop(cond_fn, step_fn, init, max_steps)
        # single merge: each slot was written (value+1) by its owner only
        merged = [
            jax.lax.psum(a, axis) - 1
            for a in (
                final.eb_pobj, final.eb_prel, final.eb_skind,
                final.eb_sa, final.eb_sb,
            )
        ]
        return (
            *merged, final.eb_count, root_has_children, final.needs_host,
            final.stats,
        )

    mapped = _shard_map(
        run,
        mesh=mesh,
        in_specs=(P(axis), P(), P(), P(), P(), P()),
        out_specs=tuple([P()] * 9),
        check_vma=False,
    )
    return jax.jit(mapped)


def get_sharded_expand_kernel(mesh: Mesh, statics: tuple, axis: str = "x"):
    key = (mesh, axis, statics)
    with _kernel_cache_lock:
        fn = _kernel_cache.pop(key, None)
        if fn is None:
            fn = _build_kernel(mesh, axis, statics)
            while len(_kernel_cache) >= _KERNEL_CACHE_CAP:
                _kernel_cache.pop(next(iter(_kernel_cache)))
        _kernel_cache[key] = fn
    return fn


def place_sharded_expand_tables(
    stacked: dict, delta_np: dict, mesh: Mesh, axis: str = "x"
) -> tuple[dict, dict]:
    import numpy as np

    from ..engine.kernel import pack_pair_table

    assert set(stacked) == set(_EXPAND_SHARDED_KEYS)
    n = stacked["fh_obj"].shape[0]
    fh_pack = np.zeros((n, stacked["fh_obj"].shape[1], 4), dtype=np.int32)
    for i in range(n):
        fh_pack[i] = pack_pair_table(
            stacked["fh_obj"][i], stacked["fh_rel"][i], stacked["fh_row"][i]
        )
    raw = {
        "fh_pack": fh_pack,
        "f_row_ptr": stacked["f_row_ptr"],
        "f_skind": stacked["f_skind"],
        "f_sa": stacked["f_sa"],
        "f_sb": stacked["f_sb"],
    }
    sharded = {
        k: jax.device_put(
            v, NamedSharding(mesh, P(axis, *([None] * (v.ndim - 1))))
        )
        for k, v in raw.items()
    }
    from ..engine.kernel import pack_delta_tables

    replicated = {
        "dirty_pack": jax.device_put(
            pack_delta_tables(delta_np)["dirty_pack"],
            NamedSharding(mesh, P()),
        )
    }
    return sharded, replicated


def sharded_expand_kernel(
    mesh: Mesh,
    sharded_tables: dict,
    replicated_tables: dict,
    q_obj, q_rel, q_depth, q_valid,
    *,
    fh_probes: int,
    max_steps: int,
    frontier_cap: int,
    edge_cap: int,
    axis: str = "x",
):
    fn = get_sharded_expand_kernel(
        mesh, (fh_probes, max_steps, frontier_cap, edge_cap), axis
    )
    return fn(sharded_tables, replicated_tables, q_obj, q_rel, q_depth, q_valid)
