#!/usr/bin/env python
"""Perf gate: compare a fresh bench record against the committed
same-backend baseline artifact and FAIL on regression.

ROADMAP item 1's "confirm-or-correct" discipline in executable form:
every bench leg claim in the repo is a committed JSON artifact, so a
fresh `bench.py` run can be diffed against the baseline mechanically —
a named metric dropping more than the threshold (default 20%) exits
non-zero with the exact numbers.

    python bench.py --platform cpu | tee /tmp/bench.out
    python tools/perf_gate.py --record /tmp/bench.out \
        --baseline BENCH_r10_cpu.json

`--record` accepts a bare JSON file OR a mixed log whose LAST
JSON-parseable line is the record (bench.py prints the record as its
final line, so `| tee` output feeds straight in). Metrics compared by
default: checks/s (`value`), deep-20 (`deep20_qps`), and — when both
artifacts carry it — bulk filtering (`filter_objects_per_sec`). A
metric absent from EITHER side is reported and skipped, not failed: the
gate compares what both runs measured. A MISSING baseline artifact or a
backend mismatch (`device`) is skip-advisory (exit 0 with the reason):
there is nothing honest to compare against — cross-backend ratios are
meaningless and a fresh clone/new box has no same-backend artifact yet.

Wired into CI as an ADVISORY step (continue-on-error): shared CI boxes
are noisy; the gate's job is to make a regression LOUD in the log, not
to hard-block on scheduler jitter. Run it locally (or on pinned
hardware) as a hard gate.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

DEFAULT_METRICS = ("value", "deep20_qps", "filter_objects_per_sec")


def load_record(path: str) -> dict:
    """A JSON object from `path`: the whole file if it parses, else the
    LAST line that parses as a JSON object (bench.py | tee logs)."""
    text = pathlib.Path(path).read_text()
    try:
        obj = json.loads(text)
        if isinstance(obj, dict):
            return obj
    except json.JSONDecodeError:
        pass
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            return obj
    raise SystemExit(f"no JSON record found in {path}")


def compare(record: dict, baseline: dict, metrics, threshold: float):
    """[(name, fresh, base, ratio, regressed)] for metrics present in
    both records; skipped names are returned separately."""
    rows, skipped = [], []
    for name in metrics:
        fresh, base = record.get(name), baseline.get(name)
        if not isinstance(fresh, (int, float)) or not isinstance(
            base, (int, float)
        ) or base <= 0:
            skipped.append(name)
            continue
        ratio = fresh / base
        rows.append((name, fresh, base, ratio, ratio < 1.0 - threshold))
    return rows, skipped


def slo_advisory(record: dict, served_p95_ms: float) -> None:
    """Advisory SLO check: the fresh record's served p95 vs the
    configured objective (`slo.objectives.served_p95_ms`, BASELINE.json's
    north-star default) — the CI bench smoke and the live SLO engine
    judging by ONE number. Advisory by design: prints, never fails (the
    regression gate above owns the exit code), and skips when the record
    carries no served leg (engine-only runs have no served p95)."""
    fresh = record.get("served_c8_p95_ms")
    if not isinstance(fresh, (int, float)):
        print("perf_gate: slo: no served leg in record — skipped")
        return
    tag = "within" if fresh <= served_p95_ms else "OVER"
    print(
        f"perf_gate: slo: served p95 {fresh:.2f} ms vs objective "
        f"{served_p95_ms:.2f} ms [{tag}] (advisory)"
    )


def closure_build_advisory(record: dict) -> None:
    """Advisory closure-build note: when the fresh record carries
    powering-build timings (the --ab-closure / --ab-powering legs),
    print the build seconds so a slowing index rebuild is LOUD in the
    CI log. Advisory by design — build time trades against coverage
    knobs (`closure.max_set_rows`) and backend, so the regression gate's
    thresholded metrics stay the only exit-code owners. Skips records
    with no closure-build leg."""
    noted = False
    for key in ("closure_build_s", "host_build_s", "device_build_s"):
        val = record.get(key)
        if isinstance(val, (int, float)):
            print(f"perf_gate: closure: {key} {val:.3f} s (advisory)")
            noted = True
    for entry in record.get("build_sweep") or ():
        if isinstance(entry, dict) and isinstance(
            entry.get("build_s"), (int, float)
        ):
            print(
                "perf_gate: closure: device build "
                f"{entry['build_s']:.3f} s @ max_set_rows="
                f"{entry.get('max_set_rows')} "
                f"hbm={entry.get('hbm_total_bytes')} B (advisory)"
            )
            noted = True
    if not noted:
        print("perf_gate: closure: no build leg in record — skipped")


def ha_failover_advisory(path: str = "HA_SMOKE_r20.json") -> None:
    """Advisory HA-failover note: print the committed HA smoke
    artifact's failover p99 (the front router's re-route latency under
    kill -9, tools/ha_smoke.py) so a regressing failover path is LOUD
    in the CI log next to the bench numbers. Advisory by design — the
    smoke itself owns pass/fail on its correctness contracts, and
    failover latency is bounded by the router's hold window, a policy
    knob rather than a bench metric. Skips silently-with-a-line when
    the artifact is absent (fresh clone) or carries no failover leg."""
    p = pathlib.Path(path)
    if not p.exists():
        print(f"perf_gate: ha: {path} not found — skipped")
        return
    try:
        doc = json.loads(p.read_text())
    except (OSError, json.JSONDecodeError):
        print(f"perf_gate: ha: {path} unreadable — skipped")
        return
    p99 = doc.get("failover_p99_ms")
    if not isinstance(p99, (int, float)):
        print(f"perf_gate: ha: no failover leg in {path} — skipped")
        return
    blackout = (doc.get("blackout_ms") or {}).get("p99")
    extra = (
        f" blackout p99 {blackout:.1f} ms"
        if isinstance(blackout, (int, float)) else ""
    )
    print(
        f"perf_gate: ha: failover p99 {p99:.2f} ms over "
        f"{doc.get('n_cycles')} kill -9 cycles{extra} "
        f"[ok={doc.get('ok')}] (advisory)"
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--record", required=True,
                    help="fresh bench output (json file or bench|tee log)")
    ap.add_argument("--baseline", required=True,
                    help="committed baseline artifact (e.g. BENCH_r10_cpu.json)")
    ap.add_argument("--metrics", nargs="*", default=list(DEFAULT_METRICS))
    ap.add_argument("--threshold", type=float, default=0.20,
                    help="max tolerated fractional regression (default 0.20)")
    ap.add_argument("--slo-served-p95-ms", type=float, default=10.0,
                    help="served-p95 SLO objective to judge the fresh "
                         "record against (advisory line; default 10, "
                         "the slo.objectives.served_p95_ms default)")
    args = ap.parse_args()

    record = load_record(args.record)
    slo_advisory(record, args.slo_served_p95_ms)
    closure_build_advisory(record)
    ha_failover_advisory()
    # SKIP-ADVISORY, not error, when there is nothing honest to compare
    # against: a missing baseline artifact or a different-backend one
    # (a fresh repo clone, a first run on new hardware, a CPU run
    # against a TPU artifact). The gate's job is catching regressions
    # vs a committed same-backend baseline; absence of one is a fact to
    # report, not a failure to page on.
    if not pathlib.Path(args.baseline).exists():
        print(
            f"perf_gate: baseline {args.baseline} not found — skipped "
            "(advisory: commit a same-backend baseline artifact to arm "
            "the gate)"
        )
        return 0
    baseline = load_record(args.baseline)

    rb, bb = record.get("device"), baseline.get("device")
    if rb and bb and rb != bb:
        print(
            f"perf_gate: backend mismatch (record={rb!r} baseline={bb!r}) "
            "— skipped (advisory: cross-backend ratios are meaningless; "
            "commit a same-backend baseline artifact to arm the gate)"
        )
        return 0

    rows, skipped = compare(record, baseline, args.metrics, args.threshold)
    rc = 0
    for name, fresh, base, ratio, regressed in rows:
        tag = "REGRESSED" if regressed else "ok"
        print(
            f"perf_gate: {name}: fresh={fresh:.1f} baseline={base:.1f} "
            f"ratio={ratio:.3f} [{tag}]"
        )
        if regressed:
            rc = 1
    for name in skipped:
        print(f"perf_gate: {name}: absent from one side — skipped")
    if not rows:
        print("perf_gate: nothing comparable — check the metric names")
        return 1
    if rc:
        print(
            f"perf_gate: FAIL — at least one metric regressed more than "
            f"{args.threshold:.0%} vs {args.baseline}"
        )
    else:
        print(f"perf_gate: ok (threshold {args.threshold:.0%})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
