#!/usr/bin/env python
"""Multi-daemon HA smoke: kill -9 the serving daemon under live load.

Supervises a real cluster — one LEADER daemon over a file-backed sqlite
store plus N watch-fed FOLLOWER daemons (keto_tpu/api/follower.py, each
cold-started from its own checkpoint and advanced by tailing the
leader's Watch changelog over gRPC) — and drives it through an HaRouter
(keto_tpu/api/router.py) while repeatedly SIGKILLing whichever daemon
answered the most recent check, restarting it, and auditing:

  1. NEVER WRONG — every answered check is audited against a
     single-writer oracle AT THE VERSION ITS RESPONSE SNAPTOKEN STAMPS.
     A follower is allowed to be stale; it is never allowed to be wrong
     at its own token. Zero tolerance.
  2. NEVER HUNG — every router call completes inside a hard wall-clock
     bound (rpc timeouts x fleet size); a single call exceeding it is a
     violation.
  3. BOUNDED FAILOVER — calls that landed on the freshly killed daemon
     fail over to a live one inside the same call; the added latency is
     recorded per call and summarized (p50/p99/max).
  4. CHANGELOG-FED STEADY STATE — while a follower is alive and
     serving, its `bootstrap_reads` counter (the ONLY path that full-
     sweeps the leader, GET /admin/ha) must not move: every version it
     serves arrived as a watch frame. Cold start is exactly ONE sweep;
     a checkpoint-restored restart resumes from its snaptoken.
  5. AGGREGATE SCALING — after the kill cycles, a closed-loop burst is
     replayed against 1, 2, ... N+1 daemons and the aggregate QPS curve
     is recorded (every answer still audited).

The daemons run `check.engine: host` (the HA plane under test is
replication/routing/failover, not the device path). Exit 0 prints one
JSON summary (also written to --out); any contract violation exits 1.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NID = "default"
FIXTURE_NAMESPACES = ("files", "groups")

# hard never-hung bound: rpc_timeout_s * (fleet + final leader retry)
# + hold_ms, with slack for process scheduling under load
RPC_TIMEOUT_S = 2.0
HUNG_CALL_S = 10.0


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def build_config(role: str, ports: dict, dsn_path: str = "",
                 leader_addr: str = "", state_dir: str = ""):
    from keto_tpu.config import Config
    from keto_tpu.namespace import Namespace

    doc = {
        "check": {"engine": "host", "cache": {"enabled": True}},
        "serve": {
            "read": {"host": "127.0.0.1", "port": ports["read"]},
            "write": {"host": "127.0.0.1", "port": ports["write"]},
            "metrics": {"host": "127.0.0.1", "port": ports["metrics"]},
        },
        # fast in-band heartbeats so follower liveness + bootstrap
        # version discovery never wait long on an idle leader
        "watch": {"heartbeat_s": 0.5, "poll_interval": 0.05},
    }
    if role == "leader":
        doc["dsn"] = f"sqlite://{dsn_path}"
    else:
        doc["dsn"] = "memory"  # ignored: the follower store is network-fed
        doc["follower"] = {
            "enabled": True,
            "leader": leader_addr,
            "liveness_s": 2.0,
            "checkpoint_s": 0.75,
            "bootstrap_page_size": 500,
            "state_dir": state_dir,
            "rpc_timeout_s": 5.0,
        }
    cfg = Config(doc)
    cfg.set_namespaces([Namespace(name=n) for n in FIXTURE_NAMESPACES])
    return cfg


def serve_child(args) -> int:
    """One daemon (leader or follower), killed at will by the supervisor."""
    from keto_tpu.api.daemon import Daemon
    from keto_tpu.registry import Registry

    ports = {"read": args.read_port, "write": args.write_port,
             "metrics": args.metrics_port}
    cfg = build_config(args.role, ports, dsn_path=args.dsn,
                       leader_addr=args.leader, state_dir=args.state_dir)
    Daemon(Registry(cfg)).serve_forever()
    return 0


def drive_child(args) -> int:
    """One closed-loop load generator process for the QPS curve: hammers
    unpinned checks through an HaRouter over the given fleet and audits
    every answer against the static fixture (the store is frozen while
    the curve runs). Prints one JSON line: {"checks": n, "wrong": n}."""
    from keto_tpu.api.router import HaRouter
    from keto_tpu.ketoapi import RelationTuple

    with open(args.fixture) as f:
        expect: dict[str, bool] = json.load(f)["tuples"]
    targets = sorted(expect)
    tuples = {t: RelationTuple.from_string(t) for t in targets}
    addrs = [a for a in args.addrs.split(",") if a]
    router = HaRouter(addrs[0], addrs[1:], leader_write=args.leader,
                      hold_ms=0.0, rpc_timeout_s=RPC_TIMEOUT_S)
    counts = [0] * args.threads
    wrong = [0] * args.threads
    stop = time.monotonic() + args.seconds

    def worker(i: int) -> None:
        lrng = random.Random(i)
        while time.monotonic() < stop:
            t_str = targets[lrng.randrange(len(targets))]
            try:
                allowed, token, _ = router.check(
                    tuples[t_str], timeout=RPC_TIMEOUT_S
                )
            except Exception:  # noqa: BLE001 — counted via missing ok
                continue
            if _token_version(token) is None or allowed != expect[t_str]:
                wrong[i] += 1
            counts[i] += 1

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(args.threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    router.close()
    print(json.dumps({"checks": sum(counts), "wrong": sum(wrong)}))
    return 0


# -- supervisor-side pieces ----------------------------------------------------


def _token_version(token: str):
    if not token:
        return None
    try:
        return int(token.rsplit("_", 1)[1])
    except (IndexError, ValueError):
        return None


class Oracle:
    """Single-writer ground truth with version-exact audits.

    The harness is the ONLY writer and never overlaps a write with a
    check, so every committed version is attributable. Each write op is
    recorded as (lo, hi, present): committed somewhere in (lo, hi], so
    membership is exact for audit versions <= lo or >= hi and unknown
    (skipped) strictly inside the interval — which only arises for the
    delete leg of a delete+marker transact."""

    def __init__(self):
        self._mu = threading.Lock()
        self._ops: dict[str, list[tuple[int, int, bool]]] = {}
        self.indeterminate: set[str] = set()

    def record(self, tuple_str: str, lo: int, hi: int, present: bool) -> None:
        with self._mu:
            self._ops.setdefault(tuple_str, []).append((lo, hi, present))

    def mark_indeterminate(self, tuple_str: str) -> None:
        with self._mu:
            self.indeterminate.add(tuple_str)

    def allowed_at(self, tuple_str: str, version: int):
        """True/False when provable at `version`, None when unknowable
        (in-flight-at-crash tuple or inside an op's commit interval)."""
        with self._mu:
            if tuple_str in self.indeterminate:
                return None
            state = False
            for lo, hi, present in self._ops.get(tuple_str, ()):
                if version >= hi:
                    state = present
                elif version > lo:
                    return None  # inside the commit window: unprovable
                else:
                    break
            return state

    def live_sample(self, rng: random.Random, k: int) -> list[str]:
        with self._mu:
            live = [
                t for t, ops in self._ops.items()
                if ops and ops[-1][2] and t not in self.indeterminate
            ]
        rng.shuffle(live)
        return live[:k]


class DaemonProc:
    """One supervised daemon child (leader or follower) on fixed ports."""

    def __init__(self, name: str, role: str, dsn: str = "",
                 leader_addr: str = "", state_dir: str = ""):
        self.name = name
        self.role = role
        self.dsn = dsn
        self.leader_addr = leader_addr
        self.state_dir = state_dir
        self.ports = {"read": free_port(), "write": free_port(),
                      "metrics": free_port()}
        self.child: subprocess.Popen | None = None
        self.restarts = 0

    @property
    def read_addr(self) -> str:
        return f"127.0.0.1:{self.ports['read']}"

    @property
    def write_addr(self) -> str:
        return f"127.0.0.1:{self.ports['write']}"

    def spawn(self) -> None:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        cmd = [
            sys.executable, os.path.abspath(__file__), "--serve",
            "--role", self.role, "--dsn", self.dsn,
            "--leader", self.leader_addr, "--state-dir", self.state_dir,
            "--read-port", str(self.ports["read"]),
            "--write-port", str(self.ports["write"]),
            "--metrics-port", str(self.ports["metrics"]),
        ]
        self.child = subprocess.Popen(
            cmd, env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def wait_ready(self, timeout: float = 90.0) -> bool:
        deadline = time.monotonic() + timeout
        url = f"http://127.0.0.1:{self.ports['read']}/health/ready"
        while time.monotonic() < deadline:
            if self.child is not None and self.child.poll() is not None:
                return False
            try:
                with urllib.request.urlopen(url, timeout=2) as r:
                    if r.status == 200:
                        return True
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.05)
        return False

    def kill(self) -> None:
        try:
            self.child.send_signal(signal.SIGKILL)
        except ProcessLookupError:
            pass
        self.child.wait(timeout=15)

    def alive(self) -> bool:
        return self.child is not None and self.child.poll() is None

    def admin_ha(self) -> dict | None:
        url = f"http://127.0.0.1:{self.ports['metrics']}/admin/ha"
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return json.load(r)
        except Exception:  # noqa: BLE001 — a dead daemon has no admin plane
            return None


def wait_follower_synced(d: DaemonProc, min_version: int,
                         timeout: float = 60.0) -> dict | None:
    """Poll /admin/ha until the follower is TAILING at >= min_version."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        last = d.admin_ha()
        if (
            last is not None
            and last.get("state") == "tailing"
            and int(last.get("applied_version", 0)) >= min_version
        ):
            return last
        time.sleep(0.05)
    return last


class Driver(threading.Thread):
    """The load: ONE writer+checker thread (so the oracle is exact; see
    Oracle docstring) hammering the HaRouter — a mix of fresh inserts,
    delete+marker transacts, pinned read-your-writes checks, and
    unpinned checks on both live and absent tuples."""

    def __init__(self, router, oracle: Oracle, rng: random.Random,
                 violations: list, vlock: threading.Lock):
        super().__init__(name="ha-smoke-driver", daemon=True)
        self.router = router
        self.oracle = oracle
        self.rng = rng
        self.violations = violations
        self.vlock = vlock
        self.stop_evt = threading.Event()
        self._mu = threading.Lock()
        self.success_times: list[float] = []
        self.last_target = "leader"
        self.last_version = 0  # newest committed version (write tokens)
        self.last_token = ""
        self.seq = 0
        self.stats = {
            "checks_ok": 0, "check_errors": 0, "refusals_409": 0,
            "writes_ok": 0, "write_errors": 0, "deletes_ok": 0,
            "pinned_checks": 0, "wrong_answers": 0, "hung_calls": 0,
        }
        self.max_call_s = 0.0

    def violation(self, kind: str, **facts) -> None:
        with self.vlock:
            self.violations.append({"kind": kind, **facts})

    def run(self) -> None:
        while not self.stop_evt.is_set():
            r = self.rng.random()
            if r < 0.12:
                self._write()
            elif r < 0.17:
                self._delete()
            else:
                self._check()
            time.sleep(0.002)

    # -- writes (leader only, through the router) ------------------------------

    def _write(self) -> None:
        from keto_tpu.ketoapi import RelationTuple

        self.seq += 1
        t = f"files:o{self.seq}#owner@u{self.seq % 7}"
        lo = self.last_version
        try:
            tokens = self.router.transact(
                insert=[RelationTuple.from_string(t)], timeout=RPC_TIMEOUT_S
            )
            v = _token_version(tokens[-1]) if tokens else None
        except Exception:  # noqa: BLE001 — leader down: write is in-flight-lost
            self.oracle.mark_indeterminate(t)
            self.stats["write_errors"] += 1
            return
        if v is None:
            self.oracle.mark_indeterminate(t)
            self.stats["write_errors"] += 1
            return
        self.oracle.record(t, lo, v, True)
        with self._mu:
            self.last_version = max(self.last_version, v)
            self.last_token = tokens[-1]
        self.stats["writes_ok"] += 1

    def _delete(self) -> None:
        from keto_tpu.ketoapi import RelationTuple

        victims = self.oracle.live_sample(self.rng, 1)
        if not victims:
            return
        victim = victims[0]
        self.seq += 1
        marker = f"files:d{self.seq}#owner@mk"
        lo = self.last_version
        try:
            # one transact: the marker insert's token upper-bounds the
            # delete's commit version (single writer => exact outside
            # the (lo, v) window)
            tokens = self.router.transact(
                insert=[RelationTuple.from_string(marker)],
                delete=[RelationTuple.from_string(victim)],
                timeout=RPC_TIMEOUT_S,
            )
            v = _token_version(tokens[-1]) if tokens else None
        except Exception:  # noqa: BLE001
            self.oracle.mark_indeterminate(victim)
            self.oracle.mark_indeterminate(marker)
            self.stats["write_errors"] += 1
            return
        if v is None:
            self.oracle.mark_indeterminate(victim)
            self.oracle.mark_indeterminate(marker)
            self.stats["write_errors"] += 1
            return
        self.oracle.record(victim, lo, v, False)
        self.oracle.record(marker, lo, v, True)
        with self._mu:
            self.last_version = max(self.last_version, v)
            self.last_token = tokens[-1]
        self.stats["deletes_ok"] += 1

    # -- checks (audited at their stamped snaptoken) ---------------------------

    def _check(self) -> None:
        from keto_tpu.ketoapi import RelationTuple

        r = self.rng.random()
        if r < 0.70:
            sample = self.oracle.live_sample(self.rng, 1)
            t = sample[0] if sample else "files:absent0#owner@nobody"
        else:
            t = f"files:absent{self.rng.randrange(16)}#owner@nobody"
        pin = ""
        pin_v = None
        if self.rng.random() < 0.35 and self.last_token:
            with self._mu:
                pin, pin_v = self.last_token, self.last_version
            self.stats["pinned_checks"] += 1
        t0 = time.monotonic()
        try:
            allowed, token, target = self.router.check(
                RelationTuple.from_string(t), snaptoken=pin,
                timeout=RPC_TIMEOUT_S,
            )
        except Exception as e:  # noqa: BLE001 — classified below
            dt = time.monotonic() - t0
            self.max_call_s = max(self.max_call_s, dt)
            if dt > HUNG_CALL_S:
                self.stats["hung_calls"] += 1
                self.violation("hung_call", tuple=t, seconds=round(dt, 3))
            code = getattr(e, "code", None)
            name = ""
            if callable(code):
                try:
                    name = code().name
                except Exception:  # noqa: BLE001
                    name = ""
            if name == "FAILED_PRECONDITION":
                self.stats["refusals_409"] += 1  # typed refusal: not wrong
            else:
                self.stats["check_errors"] += 1
            return
        dt = time.monotonic() - t0
        self.max_call_s = max(self.max_call_s, dt)
        if dt > HUNG_CALL_S:
            self.stats["hung_calls"] += 1
            self.violation("hung_call", tuple=t, seconds=round(dt, 3))
        v = _token_version(token)
        if v is None:
            self.violation("tokenless_answer", tuple=t, target=target)
            return
        if pin_v is not None and v < pin_v:
            self.violation("pinned_token_regressed", tuple=t, target=target,
                           pinned=pin_v, stamped=v)
        want = self.oracle.allowed_at(t, v)
        if want is not None and allowed != want:
            self.stats["wrong_answers"] += 1
            self.violation("wrong_answer", tuple=t, target=target,
                           version=v, got=allowed, want=want)
        self.stats["checks_ok"] += 1
        with self._mu:
            self.last_target = target
            self.success_times.append(time.monotonic())
            if len(self.success_times) > 100_000:
                del self.success_times[:50_000]

    def first_success_after(self, t: float) -> float | None:
        with self._mu:
            for ts in reversed(self.success_times):
                if ts <= t:
                    break
            for ts in self.success_times[-10_000:]:
                if ts > t:
                    return ts
        return None


def measure_qps(leader: DaemonProc, followers: list[DaemonProc],
                fixture_path: str, violations: list, vlock: threading.Lock,
                seconds: float = 2.0, procs: int = 4,
                threads: int = 3) -> dict:
    """Aggregate-QPS point for one fleet subset: `procs` independent
    load-generator PROCESSES (each its own GIL — the fleet, not the
    driver, is the bottleneck) run closed-loop for `seconds`, auditing
    every answer against the frozen-store fixture."""
    addrs = ",".join([leader.read_addr, *[f.read_addr for f in followers]])
    cmd = [
        sys.executable, os.path.abspath(__file__), "--drive",
        "--addrs", addrs, "--leader", leader.write_addr,
        "--fixture", fixture_path, "--seconds", str(seconds),
        "--threads", str(threads),
    ]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    children = [
        subprocess.Popen(cmd, env=env, cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL)
        for _ in range(procs)
    ]
    checks = wrong = 0
    for c in children:
        stdout, _ = c.communicate(timeout=120)
        try:
            doc = json.loads(stdout.decode().strip().splitlines()[-1])
        except (ValueError, IndexError):
            with vlock:
                violations.append({"kind": "qps_driver_died",
                                   "exit_code": c.returncode})
            continue
        checks += doc["checks"]
        wrong += doc["wrong"]
    if wrong:
        with vlock:
            violations.append({"kind": "wrong_answer_qps_curve",
                               "daemons": 1 + len(followers),
                               "wrong": wrong})
    return {
        "daemons": 1 + len(followers),
        "checks": checks,
        "qps": round(checks / seconds, 1),
        "wrong": wrong,
    }


# -- the run -------------------------------------------------------------------


def run(args) -> int:
    import tempfile

    from keto_tpu.api.router import HaRouter
    from keto_tpu.ketoapi import RelationTuple

    rng = random.Random(args.seed)
    base = tempfile.mkdtemp(prefix="keto-ha-smoke-")
    violations: list[dict] = []
    vlock = threading.Lock()
    out: dict = {"cycles": []}
    t_start = time.monotonic()

    leader = DaemonProc("leader", "leader",
                        dsn=os.path.join(base, "store.sqlite"))
    followers = [
        DaemonProc(f"follower-{i}", "follower",
                   state_dir=os.path.join(base, f"state-f{i}"))
        for i in range(args.followers)
    ]
    daemons = {d.name: d for d in [leader, *followers]}

    leader.spawn()
    if not leader.wait_ready():
        print(json.dumps({"ok": False, "error": "leader never ready"}))
        return 1
    for f in followers:
        f.leader_addr = leader.read_addr
        f.spawn()
    for f in followers:
        if not f.wait_ready():
            print(json.dumps({"ok": False,
                              "error": f"{f.name} never ready"}))
            return 1

    oracle = Oracle()
    router = HaRouter(
        leader.read_addr, [f.read_addr for f in followers],
        leader_write=leader.write_addr,
        hold_ms=150.0, probe_interval_s=0.25, breaker_threshold=3,
        breaker_cooldown_s=0.75, rpc_timeout_s=RPC_TIMEOUT_S,
        probe_tuple=RelationTuple.from_string("files:probe#owner@nobody"),
    )
    router.start_probes()
    driver = Driver(router, oracle, random.Random(args.seed + 1),
                    violations, vlock)
    driver.start()

    # warm up: traffic flowing, then every follower tailing at the tip
    time.sleep(1.5)
    with driver._mu:
        tip = driver.last_version
    cold_bootstraps = {}
    for f in followers:
        st = wait_follower_synced(f, tip)
        cold_bootstraps[f.name] = None if st is None else st.get(
            "bootstrap_reads"
        )
        # COLD START pin: exactly one full sweep, ever
        if st is None or st.get("bootstrap_reads") != 1:
            violations.append({
                "kind": "cold_start_bootstrap_count", "daemon": f.name,
                "status": st,
            })
    out["cold_start_bootstrap_reads"] = cold_bootstraps

    def rotation_restored(timeout: float = 15.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(t["in_rotation"] for t in router.status()["targets"]):
                return True
            time.sleep(0.1)
        return False

    restart_bootstraps = 0
    for cycle in range(args.cycles):
        # steady-state bootstrap baseline across live followers
        b0 = {f.name: (f.admin_ha() or {}).get("bootstrap_reads")
              for f in followers if f.alive()}
        time.sleep(0.6)  # drive with the full fleet
        b1 = {f.name: (f.admin_ha() or {}).get("bootstrap_reads")
              for f in followers if f.alive()}
        for name, v0 in b0.items():
            if v0 is not None and b1.get(name) is not None and b1[name] != v0:
                violations.append({
                    "kind": "steady_state_bootstrap_reads", "cycle": cycle,
                    "daemon": name, "before": v0, "after": b1[name],
                })
        with driver._mu:
            victim_name = driver.last_target
        victim = daemons.get(victim_name, leader)
        failovers_before = router.stats["failovers"]
        fo_ms_before = len(router.failover_ms)
        kill_t = time.monotonic()
        victim.kill()
        time.sleep(1.2)  # drive with a hole in the fleet
        first_ok = driver.first_success_after(kill_t)
        blackout_ms = (
            None if first_ok is None else round((first_ok - kill_t) * 1e3, 3)
        )
        victim.restarts += 1
        victim.spawn()
        ready = victim.wait_ready()
        restart: dict = {"ready": ready}
        if ready and victim.role == "follower":
            st = wait_follower_synced(victim, 0)
            if st is not None:
                restart.update({
                    "restored_from_checkpoint": st["checkpoint"]["restored"],
                    "bootstrap_reads": st.get("bootstrap_reads"),
                    "applied_version": st.get("applied_version"),
                })
                restart_bootstraps += int(st.get("bootstrap_reads") or 0)
        if not ready:
            violations.append({"kind": "restart_failed", "cycle": cycle,
                               "daemon": victim.name})
        rotation_ok = rotation_restored()
        record = {
            "cycle": cycle,
            "victim": victim.name,
            "role": victim.role,
            "blackout_ms": blackout_ms,
            "failovers": router.stats["failovers"] - failovers_before,
            "failover_ms": [
                round(v, 3) for v in router.failover_ms[fo_ms_before:]
            ][:50],
            "restart": restart,
            "rotation_restored": rotation_ok,
        }
        out["cycles"].append(record)
        print(json.dumps(record), file=sys.stderr)

    driver.stop_evt.set()
    driver.join(timeout=30)
    status = router.status()
    router.close()

    # aggregate-QPS-vs-daemon-count curve (all daemons back up, store
    # frozen: the oracle's tip answers become a static audit fixture)
    with driver._mu:
        tip = driver.last_version
    for f in followers:
        wait_follower_synced(f, tip)
    expect = {}
    for t in oracle.live_sample(rng, 24):
        want = oracle.allowed_at(t, tip)
        if want is not None:
            expect[t] = want
    for i in range(8):
        expect[f"files:absent{i}#owner@nobody"] = False
    fixture_path = os.path.join(base, "qps_fixture.json")
    with open(fixture_path, "w") as f:
        json.dump({"tuples": expect, "tip": tip}, f)
    curve = []
    for n in range(0, len(followers) + 1):
        curve.append(measure_qps(leader, followers[:n], fixture_path,
                                 violations, vlock))
        print(json.dumps(curve[-1]), file=sys.stderr)

    for d in daemons.values():
        if d.alive():
            d.kill()

    blackouts = sorted(
        c["blackout_ms"] for c in out["cycles"]
        if c["blackout_ms"] is not None
    )

    def q(xs: list, p: float):
        if not xs:
            return None
        return round(xs[min(len(xs) - 1, int(p * len(xs)))], 3)

    out.update({
        "n_cycles": args.cycles,
        "n_daemons": 1 + len(followers),
        # the curve is only a SCALING measurement when the fleet has
        # cores to scale onto: on a single-core host every daemon and
        # every driver timeshares one CPU, so aggregate QPS is flat-to-
        # inverted by contention and the curve degenerates to a
        # correctness burst (still audited, still committed)
        "host_cpus": os.cpu_count(),
        "duration_s": round(time.monotonic() - t_start, 1),
        "driver": dict(driver.stats),
        "max_call_s": round(driver.max_call_s, 3),
        "router": status,
        "failover_p99_ms": status["failover_latency_ms"]["p99"],
        "blackout_ms": {"p50": q(blackouts, 0.5), "p99": q(blackouts, 0.99),
                        "max": blackouts[-1] if blackouts else None},
        "restart_bootstrap_reads": restart_bootstraps,
        "qps_curve": curve,
        "violations": violations,
        "ok": not violations,
    })
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    return 0 if out["ok"] else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serve", action="store_true",
                    help="child: run one daemon")
    ap.add_argument("--drive", action="store_true",
                    help="child: one QPS-curve load generator")
    ap.add_argument("--role", default="leader",
                    choices=("leader", "follower"))
    ap.add_argument("--dsn", default="")
    ap.add_argument("--leader", default="",
                    help="child: leader host:port (follower tail / writes)")
    ap.add_argument("--state-dir", default="")
    ap.add_argument("--addrs", default="",
                    help="drive child: comma-joined fleet read addrs")
    ap.add_argument("--fixture", default="",
                    help="drive child: audit fixture path")
    ap.add_argument("--seconds", type=float, default=2.0)
    ap.add_argument("--threads", type=int, default=3)
    ap.add_argument("--read-port", type=int, default=0)
    ap.add_argument("--write-port", type=int, default=0)
    ap.add_argument("--metrics-port", type=int, default=0)
    ap.add_argument("--cycles", type=int, default=12,
                    help="kill -9/restart cycles")
    ap.add_argument("--followers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=20)
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    if args.serve:
        return serve_child(args)
    if args.drive:
        return drive_child(args)
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
