"""Batched reverse-reachability kernels: ListObjects / ListSubjects.

The forward check kernel answers "may S do R on O?"; Zanzibar's hardest
production query family is the inverse — "which objects can this subject
reach?" (served there by the Leopard set index) and its dual "which
subjects reach this object?". Both are set-valued graph joins that batch
into the same bucketized-gather shape the check kernel runs (TrieJax /
GraphBLAS formulation: frontier expansion = batched sparse gather), so
they ride the identical backend-selected bounded loop
(engine/kernel.bounded_loop), dedupe, and cause-coded host-fallback
machinery.

ListObjects — reverse BFS over the TRANSPOSED mirror
(snapshot.build_reverse_tables / build_reverse_programs):

  seeds: the reverse-seed CSR row for the query's exact subject key —
    precisely the nodes whose direct probe the forward kernel would hit.
  per step, each frontier task (query, obj, rel, depth):
    1. flag_phase on the VISITED node (config-missing / relation-not-
       found / island / host-only programs host-flag the query, same
       codes as check) + reverse-dirty overlay probe (CAUSE_DIRTY)
    2. emit `obj` into the query's result pool when the node matches the
       query's (namespace, relation) filter and depth >= 0
    3. expand to PREDECESSORS: the reverse-edge CSR row keyed by `obj`
       inverts checkExpandSubject (edge sb == task rel, task rel not
       wildcard -> pred (edge obj, edge rel) at depth-1) and TTU
       instructions (inverted entry (ns, rel_p, rel_t) with edge rel ==
       rel_t and edge-obj namespace == ns -> pred (edge obj, rel_p) at
       depth-1); inverted COMPUTED entries add (obj, rel_p) at the SAME
       depth. POISON entries (AND-island leaf relations) host-flag the
       query instead of expanding — island members are not enumerable by
       pure-OR propagation.
    4. dedupe on (query, obj, rel) keeping the deepest remaining depth
       (kernel.dedupe_phase, unchanged).

  Exactness: device-exact on the monotone fragment; AND islands flag via
  poison entries (a member of an AND implies every leaf sub-check is a
  member, so the walk reaches a leaf relation before the island's
  members could be silently missed); any NOT in the config disables the
  device path entirely (snapshot.build_reverse_programs host_all) — NOT
  members exist exactly where NO path exists, which reachability cannot
  observe. Frontier/result/seed overflow, dirty rows, and step-budget
  exhaustion flag their query; the facade replays flagged queries on the
  exact host oracle (engine/reference.py list_objects).

ListSubjects — forward BFS from one (obj, rel) node over the full-edge
CSR (expand_kernel.build_full_csr: plain leaves AND subject-set
children) PLUS the compiled rewrite instructions (unlike Expand, which
follows stored tuples only): every visited node's plain-subject edges
are results when depth >= 1 (the forward direct probe's depth rule);
subject-set edges and COMPUTED/TTU instructions continue the walk with
check's exact depth bookkeeping. Same flag/fallback contract.

Both kernels use packed single-buffer I/O (one upload, one readback per
batch — the axon-tunnel transfer-count floor, see check_kernel_packed).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .delta import DELTA_PROBES, DIRTY_FOR_EXPAND
from .kernel import (
    CAUSE_DIRTY,
    CAUSE_FRONTIER_OVERFLOW,
    CAUSE_ISLAND_HOST,
    CAUSE_STEP_EXHAUSTED,
    Expansion,
    N_LAUNCH_STATS,
    _isolate,
    _multi_pair_key_probe,
    bounded_loop,
    dedupe_phase,
    empty_launch_stats,
    flag_phase,
    pack_instr_table,
    pack_pair_table,
    pack_rh_span_table,
    program_lookup,
    scan_seg_map_backend,
    update_launch_stats,
)
from .snapshot import (
    EMPTY,
    INSTR_COMPUTED,
    INSTR_TTU,
    RINSTR_COMPUTED,
    RINSTR_POISON,
    RINSTR_TTU,
    GraphSnapshot,
    build_reverse_programs,
    build_reverse_tables,
    reverse_subject_tag,
)


# -- host state builders (mirror expand_kernel.build_full_csr*) ----------------


def build_reverse_state(
    tuples: Sequence, snapshot: GraphSnapshot, namespaces, view=None
) -> dict:
    """Transposed mirror + inverted programs from per-tuple objects;
    tuples unknown to the view drop (their rows are reverse-dirty-flagged
    or beyond this state's staleness horizon, like build_full_csr)."""
    from .delta import SnapshotView

    view = view or SnapshotView(snapshot)
    n_t = len(tuples)
    t_obj = np.zeros(n_t, dtype=np.int32)
    t_rel = np.zeros(n_t, dtype=np.int32)
    t_skind = np.zeros(n_t, dtype=np.int32)
    t_sa = np.zeros(n_t, dtype=np.int32)
    t_sb = np.zeros(n_t, dtype=np.int32)
    keep = np.zeros(n_t, dtype=bool)
    for i, t in enumerate(tuples):
        node = view.encode_node(t.namespace, t.object, t.relation)
        subject = view.encode_subject(t)
        if node is None or subject is None:
            continue
        t_obj[i], t_rel[i] = node
        t_skind[i], t_sa[i], t_sb[i] = subject
        keep[i] = True
    return _reverse_state_from_encoded(
        t_obj[keep], t_rel[keep], t_skind[keep], t_sa[keep], t_sb[keep],
        snapshot, namespaces,
    )


def build_reverse_state_columnar(cols, snapshot: GraphSnapshot, namespaces) -> dict:
    """Columnar twin: vectorized encoding against the snapshot vocab
    (no per-tuple Python on the 1e7+ ingest path)."""
    from .snapshot import encode_edge_columns

    t_obj, t_rel, t_skind, t_sa, t_sb, keep = encode_edge_columns(cols, snapshot)
    k = np.flatnonzero(keep)
    return _reverse_state_from_encoded(
        t_obj[k], t_rel[k], t_skind[k], t_sa[k], t_sb[k], snapshot, namespaces
    )


def _reverse_state_from_encoded(
    t_obj, t_rel, t_skind, t_sa, t_sb, snapshot: GraphSnapshot, namespaces
) -> dict:
    state = build_reverse_tables(t_obj, t_rel, t_skind, t_sa, t_sb)
    (
        rinstr_kind, rinstr_relp, rinstr_relt, rinstr_ns, RK, host_all,
    ) = build_reverse_programs(
        namespaces, snapshot.ns_ids, snapshot.rel_ids, snapshot.n_config_rels
    )
    state.update(
        rinstr_kind=rinstr_kind, rinstr_relp=rinstr_relp,
        rinstr_relt=rinstr_relt, rinstr_ns=rinstr_ns,
        RK=RK, host_all=host_all, garbage=0,
    )
    return state


def pack_rinstr_table(kind, relp, relt, ns) -> np.ndarray:
    """Interleave the inverted-instruction columns into [NR, RK*4] rows
    of (kind, rel_p, rel_t, ns) lanes — one row-gather per task."""
    NR, RK = kind.shape
    out = np.zeros((NR, RK, 4), dtype=np.int32)
    out[..., 0] = kind
    out[..., 1] = relp
    out[..., 2] = relt
    out[..., 3] = ns
    return out.reshape(NR, RK * 4)


def pack_reverse_tables(rnp: dict, snapshot: GraphSnapshot) -> dict:
    """Host reverse-state arrays -> the device table dict the reverse
    kernel closes over. Spans resolve into the row-hash value lanes at
    pack time (pack_rh_span_table) so row lookups ride the probe's own
    bucket-row fetch, exactly like the forward rh table."""
    return {
        "rvh_pack": pack_rh_span_table(
            rnp["rvh_obj"], rnp["rvh_rel"], rnp["rvh_row"], rnp["rv_row_ptr"]
        ),
        "rv_pack": pack_pair_table(rnp["rv_pobj"], rnp["rv_prel"], rnp["rv_sb"]),
        "rsh_pack": pack_rh_span_table(
            rnp["rsh_obj"], rnp["rsh_tag"], rnp["rsh_row"], rnp["rs_row_ptr"]
        ),
        "rs_pack": np.stack(
            [np.asarray(rnp["rs_obj"]), np.asarray(rnp["rs_rel"])], axis=-1
        ).astype(np.int32),
        "rinstr_pack": pack_rinstr_table(
            rnp["rinstr_kind"], rnp["rinstr_relp"],
            rnp["rinstr_relt"], rnp["rinstr_ns"],
        ),
        "objslot_ns": np.asarray(snapshot.objslot_ns),
        "ns_has_config": np.asarray(snapshot.ns_has_config),
        "prog_flags": np.asarray(snapshot.prog_flags),
    }


def pack_subjects_tables(csr: dict, snapshot: GraphSnapshot) -> dict:
    """Full-edge CSR (expand_kernel.build_full_csr output) -> the
    list-subjects device tables: span-resolved fh row table + packed
    (skind, sa, sb) edge rows + the check kernel's instruction lanes."""
    return {
        "fsh_pack": pack_rh_span_table(
            csr["fh_obj"], csr["fh_rel"], csr["fh_row"], csr["f_row_ptr"]
        ),
        "fe_pack": pack_pair_table(csr["f_skind"], csr["f_sa"], csr["f_sb"]),
        "instr_pack": pack_instr_table(
            snapshot.instr_kind, snapshot.instr_rel, snapshot.instr_rel2
        ),
        "objslot_ns": np.asarray(snapshot.objslot_ns),
        "ns_has_config": np.asarray(snapshot.ns_has_config),
        "prog_flags": np.asarray(snapshot.prog_flags),
    }


# -- shared device helpers -----------------------------------------------------


def _span_probe(tables, prefix: str, k1, k2, probes: int):
    """(start[F], len[F]) of the CSR row keyed (k1, k2) in a
    span-resolved pair table ({prefix}_pack); EMPTY rows -> len 0."""
    spans = _multi_pair_key_probe(
        tables, prefix, k1, k2[:, None], probes, n_vals=2
    )[:, 0, :]
    start = spans[..., 0]
    length = jnp.where(start < 0, 0, spans[..., 1] - start)
    return start, length


def _seg_map(offsets: jnp.ndarray, flat_counts: jnp.ndarray, F: int):
    """Covering-segment map over a [F] work list (backend-picked, see
    kernel.expand_phase): slot j -> the segment whose span contains j."""
    n_seg = flat_counts.shape[0]
    j = jnp.arange(F, dtype=jnp.int32)
    if scan_seg_map_backend():
        startpos = jnp.where(flat_counts > 0, offsets, F)
        marks = jnp.zeros(F, jnp.int32).at[startpos].max(
            jnp.arange(1, n_seg + 1, dtype=jnp.int32), mode="drop"
        )
        seg = jax.lax.cummax(marks) - 1
    else:
        seg = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32) - 1
    return jnp.clip(seg, 0, n_seg - 1), j


def _bump_emit(q, emit, counts_so_far, F: int, B: int):
    """Per-query bump allocation for <=1 emission per task: returns
    (slot_within_query[F]) for emitting tasks (garbage elsewhere). Same
    sort + segmented-scan construction as the expand kernel's edge
    buffer."""
    inc = emit.astype(jnp.int32)
    order = jnp.argsort(q + jnp.where(emit, 0, B))
    sq = q[order]
    scounts = inc[order]
    cum = jnp.cumsum(scounts) - scounts
    seg_first = jnp.concatenate([jnp.ones(1, dtype=bool), sq[1:] != sq[:-1]])
    seg_base = jnp.where(seg_first, cum, 0)
    seg_base = jax.lax.associative_scan(jnp.maximum, seg_base)
    within_q = cum - seg_base
    inv = jnp.zeros(F, dtype=jnp.int32).at[order].set(
        jnp.arange(F, dtype=jnp.int32)
    )
    return counts_so_far[q] + within_q[inv]


def _rd_lookup(tables, k1, k2):
    """Reverse-dirty probe: nonzero when the (key, tag) entry is marked
    in the delta's rd table (0 when clean)."""
    val = _multi_pair_key_probe(tables, "rd", k1, k2[:, None], DELTA_PROBES)[
        :, 0
    ]
    return jnp.maximum(val, 0)


# -- ListObjects: reverse BFS --------------------------------------------------


class _RevState(NamedTuple):
    t_q: jnp.ndarray  # [F]
    t_obj: jnp.ndarray  # [F]
    t_rel: jnp.ndarray  # [F]
    t_depth: jnp.ndarray  # [F] remaining depth (D - consumed)
    n_tasks: jnp.ndarray
    res_obj: jnp.ndarray  # [B * R] matched object slots (strided)
    res_count: jnp.ndarray  # [B]
    needs_host: jnp.ndarray  # [B] CAUSE_* code
    step: jnp.ndarray
    stats: jnp.ndarray  # [N_LAUNCH_STATS] launch introspection counters


_REVERSE_STATICS = (
    "rvh_probes", "rsh_probes", "RK", "max_steps", "wildcard_rel",
    "n_config_rels", "frontier_cap", "result_cap", "has_delta",
)


def _list_objects_impl(
    tables: dict,
    q_sa: jnp.ndarray,  # [B] subject id / subject-set object slot
    q_tag: jnp.ndarray,  # [B] reverse_subject_tag of the subject
    q_ns: jnp.ndarray,  # [B] target namespace id (result filter)
    q_rel: jnp.ndarray,  # [B] target relation id (result filter)
    q_depth: jnp.ndarray,  # [B] clamped max depth
    q_valid: jnp.ndarray,  # [B] bool
    *,
    rvh_probes: int,
    rsh_probes: int,
    RK: int,
    max_steps: int,
    wildcard_rel: int,
    n_config_rels: int,
    frontier_cap: int,
    result_cap: int,
    has_delta: bool,
):
    """Returns (res_obj [B*R], res_count [B], needs_host [B])."""
    B = q_sa.shape[0]
    F = frontier_cap
    R = result_cap
    S = 1 + RK  # expansion slots: reverse-ES row + inverted instructions
    n_redges = tables["rv_pack"].shape[0]
    n_sedges = tables["rs_pack"].shape[0]
    NCR = max(n_config_rels, 1)

    # -- seed: the reverse-seed CSR row for each query's subject key ----------
    s_start, s_len = _span_probe(tables, "rsh", q_sa, q_tag, rsh_probes)
    seed_counts = jnp.where(q_valid, s_len, 0)
    needs_host = jnp.zeros(B, dtype=jnp.int32)
    if has_delta:
        # the subject's direct-edge set changed since the base snapshot:
        # the seed row is stale either way (insert or tombstone)
        seed_dirty = q_valid & (_rd_lookup(tables, q_sa, q_tag) != 0)
        needs_host = jnp.where(seed_dirty, CAUSE_DIRTY, needs_host)
    offsets = jnp.cumsum(seed_counts) - seed_counts
    total = offsets[-1] + seed_counts[-1]
    # queries whose seed span crosses the frontier: host replay
    needs_host = jnp.maximum(
        needs_host,
        jnp.where(
            ((offsets + seed_counts) > F) & (seed_counts > 0),
            CAUSE_FRONTIER_OVERFLOW, 0,
        ).astype(jnp.int32),
    )
    seg, j = _seg_map(offsets, seed_counts, F)
    in_range = j < jnp.minimum(total, F)
    e = jnp.clip(s_start[seg] + (j - offsets[seg]), 0, max(n_sedges - 1, 0))
    if n_sedges:
        sp = _isolate(tables["rs_pack"][e])  # [F, 2] = (obj, rel)
        seed_obj, seed_rel = sp[:, 0], sp[:, 1]
    else:
        seed_obj = jnp.zeros(F, jnp.int32)
        seed_rel = jnp.zeros(F, jnp.int32)
    init = _RevState(
        t_q=jnp.where(in_range, seg, 0),
        t_obj=jnp.where(in_range, seed_obj, 0),
        # a direct hit consumes one depth unit (checkDirect runs at
        # restDepth-1), so seeds enter at D-1; emission requires >= 0
        t_rel=jnp.where(in_range, seed_rel, 0),
        t_depth=jnp.where(in_range, q_depth[seg] - 1, -1),
        n_tasks=jnp.minimum(total, F).astype(jnp.int32),
        res_obj=jnp.full(B * R, EMPTY, jnp.int32),
        res_count=jnp.zeros(B, jnp.int32),
        needs_host=needs_host,
        step=jnp.int32(0),
        stats=empty_launch_stats(),
    )

    def step_fn(st: _RevState) -> _RevState:
        idx = jnp.arange(F, dtype=jnp.int32)
        q, obj, rel, depth = st.t_q, st.t_obj, st.t_rel, st.t_depth
        live = (idx < st.n_tasks) & (st.needs_host[q] == 0)

        # 1. visited-node flags (same codes + exclusivity as check)
        prog = program_lookup(tables, obj, rel, live, n_config_rels=NCR)
        ns_t = prog[0]
        flagged = flag_phase(
            tables, obj, rel, live, n_config_rels=NCR, island_is_host=True,
            prog=prog,
        )
        needs_host = st.needs_host.at[q].max(flagged)
        if has_delta:
            zero = jnp.zeros_like(obj)
            row_dirty = live & (_rd_lookup(tables, obj, zero) != 0)
            needs_host = needs_host.at[q].max(
                jnp.where(row_dirty, CAUSE_DIRTY, 0).astype(jnp.int32)
            )

        # 2. result emission: the node matches its query's target filter
        match = (
            live
            & (rel == q_rel[q])
            & (ns_t == q_ns[q])
            & (depth >= 0)
        )
        alloc = _bump_emit(q, match, st.res_count, F, B)
        res_over = match & (alloc >= R)
        needs_host = needs_host.at[q].max(
            jnp.where(res_over, CAUSE_FRONTIER_OVERFLOW, 0).astype(jnp.int32)
        )
        emit = match & ~res_over
        dest = jnp.where(emit, q * R + alloc, B * R)
        res_obj = st.res_obj.at[dest].set(obj, mode="drop")
        res_count = st.res_count.at[q].add(emit.astype(jnp.int32))

        # 3. predecessor expansion -------------------------------------------
        # reverse-edge row keyed by the task's object slot
        zero = jnp.zeros_like(obj)
        rstart, rlen = _span_probe(tables, "rvh", obj, zero, rvh_probes)

        # inverted-instruction row keyed by the task's relation
        has_ri = live & (rel < NCR)
        ripack = _isolate(
            tables["rinstr_pack"][jnp.where(has_ri, rel, 0)]
        ).reshape(F, RK, 4)
        rik = jnp.where(has_ri[:, None], ripack[..., 0], 0)
        rip = ripack[..., 1]
        rit = ripack[..., 2]
        rin = ripack[..., 3]

        # POISON: an AND-island program pulls from this relation — its
        # members are not pure-OR-enumerable, so the query goes to host
        poison = live & jnp.any(
            (rik == RINSTR_POISON) & ((rin == -1) | (rin == ns_t[:, None])),
            axis=1,
        )
        needs_host = needs_host.at[q].max(
            jnp.where(poison, CAUSE_ISLAND_HOST, 0).astype(jnp.int32)
        )

        can_es = live & (depth >= 1) & (rel != wildcard_rel)
        is_rc = (rik == RINSTR_COMPUTED) & live[:, None] & (
            rin == ns_t[:, None]
        )
        is_rt = (rik == RINSTR_TTU) & (live & (depth >= 1))[:, None]
        counts = jnp.concatenate(
            [
                jnp.where(can_es, rlen, 0)[:, None],
                jnp.where(is_rc, 1, jnp.where(is_rt, rlen[:, None], 0)),
            ],
            axis=1,
        )  # [F, S]
        slot_kind = jnp.concatenate(
            [
                jnp.zeros((F, 1), jnp.int32),
                jnp.where(is_rc, 1, jnp.where(is_rt, 2, 0)),
            ],
            axis=1,
        )

        flat_counts = counts.reshape(-1)
        offsets = jnp.cumsum(flat_counts) - flat_counts
        total = offsets[-1] + flat_counts[-1]
        truncated = (offsets + flat_counts) > F
        seg_q = jnp.repeat(q, S, total_repeat_length=F * S)
        needs_host = needs_host.at[seg_q].max(
            jnp.where(
                truncated & (flat_counts > 0), CAUSE_FRONTIER_OVERFLOW, 0
            ).astype(jnp.int32)
        )

        seg, j = _seg_map(offsets, flat_counts, F)
        in_range = j < jnp.minimum(total, F)

        # ONE [F, 16] row-gather of the stacked per-(task, slot) source
        # matrix (same gather-volume lever as check's expand_phase)
        srcmat = jnp.stack(
            [
                jnp.broadcast_to(q[:, None], (F, S)),
                jnp.broadcast_to(obj[:, None], (F, S)),
                jnp.broadcast_to(rel[:, None], (F, S)),
                jnp.broadcast_to(depth[:, None], (F, S)),
                jnp.broadcast_to(rstart[:, None], (F, S)),
                slot_kind,
                jnp.concatenate([jnp.zeros((F, 1), jnp.int32), rip], axis=1),
                jnp.concatenate([jnp.zeros((F, 1), jnp.int32), rit], axis=1),
                jnp.concatenate(
                    [jnp.full((F, 1), -2, jnp.int32), rin], axis=1
                ),
                offsets.reshape(F, S),
                *(
                    jnp.zeros((F, S), jnp.int32)
                    for _ in range(6)
                ),  # pad to a 16-lane (64 B) gather row
            ],
            axis=-1,
        ).reshape(F * S, 16)
        src = _isolate(srcmat[seg])
        src_q = src[:, 0]
        src_obj = src[:, 1]
        src_rel = src[:, 2]
        src_depth = src[:, 3]
        src_start = src[:, 4]
        src_kind = src[:, 5]
        src_relp = src[:, 6]
        src_relt = src[:, 7]
        src_ns = src[:, 8]
        within = j - src[:, 9]

        e = jnp.clip(src_start + within, 0, max(n_redges - 1, 0))
        if n_redges:
            ep = _isolate(tables["rv_pack"][e])  # (p_obj, p_rel, e_sb, 0)
            p_obj, p_rel, e_sb = ep[:, 0], ep[:, 1], ep[:, 2]
        else:
            p_obj = jnp.zeros(F, jnp.int32)
            p_rel = jnp.zeros(F, jnp.int32)
            e_sb = jnp.zeros(F, jnp.int32)
        p_ns = tables["objslot_ns"][jnp.clip(p_obj, 0, None)]

        is_es = src_kind == 0
        is_c = src_kind == 1
        child_obj = jnp.where(is_c, src_obj, p_obj)
        child_rel = jnp.where(is_es, p_rel, src_relp)
        child_depth = jnp.where(is_c, src_depth, src_depth - 1)
        cond = jnp.where(
            is_es,
            e_sb == src_rel,
            is_c | ((p_rel == src_relt) & (p_ns == src_ns)),
        )
        children = Expansion(
            q=src_q, ctx=src_q, obj=child_obj, rel=child_rel,
            depth=child_depth, valid=in_range & cond,
        )
        nt_q, _nt_ctx, nt_obj, nt_rel, nt_depth, n_new, overflow_q = (
            dedupe_phase(children, F, B)
        )
        needs_host = jnp.maximum(needs_host, overflow_q)
        stats = update_launch_stats(
            st.stats,
            st.n_tasks,
            (live & (depth >= 0)).sum(),
            emit.sum(),
            children.valid.sum(),
            n_new,
        )
        return _RevState(
            nt_q, nt_obj, nt_rel, nt_depth, n_new,
            res_obj, res_count, needs_host, st.step + 1, stats,
        )

    def cond_fn(st: _RevState):
        return (
            (st.step < max_steps)
            & (st.n_tasks > 0)
            & ~jnp.all(st.needs_host > 0)
        )

    final = bounded_loop(cond_fn, step_fn, init, max_steps)
    # step budget ran out with live tasks: the walk did NOT finish —
    # those queries' enumerations may be incomplete (host replay)
    exhausted = (final.step >= max_steps) & (final.n_tasks > 0)
    live = jnp.arange(F, dtype=jnp.int32) < final.n_tasks
    needs_host = final.needs_host.at[final.t_q].max(
        jnp.where(exhausted & live, CAUSE_STEP_EXHAUSTED, 0).astype(jnp.int32)
    )
    return final.res_obj, final.res_count, needs_host, final.stats


@functools.partial(
    jax.jit, static_argnames=_REVERSE_STATICS + ("pool_cap",)
)
def list_objects_kernel_packed(
    tables: dict,
    qpack: jnp.ndarray,  # [6, B] int32: sa, tag, ns, rel, depth, valid
    *,
    rvh_probes: int,
    rsh_probes: int,
    RK: int,
    max_steps: int,
    wildcard_rel: int,
    n_config_rels: int,
    frontier_cap: int,
    result_cap: int,
    pool_cap: int,
    has_delta: bool,
):
    """Single-buffer I/O + device-side compaction: ONE int32 vector
    [ offsets (B+1) | needs_host (B) | stats (N_LAUNCH_STATS) |
    pool rows (pool_cap) ]; query i's matched object slots live at
    pool[offsets[i]:offsets[i+1]] (may contain revisit duplicates — the
    host decoder dedupes)."""
    B = qpack.shape[1]
    R = result_cap
    res_obj, res_count, needs_host, stats = _list_objects_impl(
        tables,
        qpack[0], qpack[1], qpack[2], qpack[3], qpack[4],
        qpack[5].astype(bool),
        rvh_probes=rvh_probes, rsh_probes=rsh_probes, RK=RK,
        max_steps=max_steps, wildcard_rel=wildcard_rel,
        n_config_rels=n_config_rels, frontier_cap=frontier_cap,
        result_cap=result_cap, has_delta=has_delta,
    )
    counts = jnp.clip(res_count, 0, R)
    offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts).astype(jnp.int32)]
    )
    j = jnp.arange(pool_cap, dtype=jnp.int32)
    seg = jnp.searchsorted(offs[1:], j, side="right").astype(jnp.int32)
    seg_c = jnp.clip(seg, 0, B - 1)
    within = j - offs[seg_c]
    valid = (j < offs[B]) & (seg < B)
    src = jnp.clip(seg_c * R + within, 0, B * R - 1)
    pool = jnp.where(valid, res_obj[src], EMPTY)
    needs_host = jnp.maximum(
        needs_host,
        jnp.where(
            (offs[1:] > pool_cap) & (counts > 0), CAUSE_FRONTIER_OVERFLOW, 0
        ).astype(jnp.int32),
    )
    offs = jnp.minimum(offs, pool_cap)
    return jnp.concatenate([offs, needs_host, stats.astype(jnp.int32), pool])


def unpack_list_results(flat: np.ndarray, B: int):
    """(offsets[B+1], needs_host[B] cause codes, pool values,
    stats[N_LAUNCH_STATS])."""
    offs = flat[: B + 1]
    needs = flat[B + 1 : 2 * B + 1]
    stats = flat[2 * B + 1 : 2 * B + 1 + N_LAUNCH_STATS]
    pool = flat[2 * B + 1 + N_LAUNCH_STATS :]
    return offs, needs, pool, stats


# -- ListSubjects: forward BFS with subject emission ---------------------------


class _SubState(NamedTuple):
    t_q: jnp.ndarray
    t_obj: jnp.ndarray
    t_rel: jnp.ndarray
    t_depth: jnp.ndarray
    n_tasks: jnp.ndarray
    res_sub: jnp.ndarray  # [B * R] plain subject ids (strided)
    res_count: jnp.ndarray  # [B]
    needs_host: jnp.ndarray  # [B] CAUSE_* code
    step: jnp.ndarray
    stats: jnp.ndarray  # [N_LAUNCH_STATS] launch introspection counters


_SUBJECTS_STATICS = (
    "K", "fsh_probes", "max_steps", "wildcard_rel", "n_config_rels",
    "frontier_cap", "result_cap", "has_delta",
)


def _list_subjects_impl(
    tables: dict,
    q_obj: jnp.ndarray,  # [B]
    q_rel: jnp.ndarray,  # [B]
    q_depth: jnp.ndarray,  # [B]
    q_valid: jnp.ndarray,  # [B]
    *,
    K: int,
    fsh_probes: int,
    max_steps: int,
    wildcard_rel: int,
    n_config_rels: int,
    frontier_cap: int,
    result_cap: int,
    has_delta: bool,
):
    """Returns (res_sub [B*R], res_count [B], needs_host [B])."""
    B = q_obj.shape[0]
    F = frontier_cap
    R = result_cap
    S = K + 1
    n_edges = tables["fe_pack"].shape[0]
    NCR = max(n_config_rels, 1)

    pad = F - B
    init = _SubState(
        t_q=jnp.pad(jnp.arange(B, dtype=jnp.int32), (0, pad)),
        t_obj=jnp.pad(q_obj.astype(jnp.int32), (0, pad)),
        t_rel=jnp.pad(q_rel.astype(jnp.int32), (0, pad)),
        t_depth=jnp.where(
            jnp.pad(q_valid, (0, pad), constant_values=False),
            jnp.pad(q_depth.astype(jnp.int32), (0, pad)),
            -1,
        ),
        n_tasks=jnp.int32(B),
        res_sub=jnp.full(B * R, EMPTY, jnp.int32),
        res_count=jnp.zeros(B, jnp.int32),
        needs_host=jnp.zeros(B, dtype=jnp.int32),
        step=jnp.int32(0),
        stats=empty_launch_stats(),
    )

    def step_fn(st: _SubState) -> _SubState:
        idx = jnp.arange(F, dtype=jnp.int32)
        q, obj, rel, depth = st.t_q, st.t_obj, st.t_rel, st.t_depth
        live = (idx < st.n_tasks) & (st.needs_host[q] == 0)

        prog = program_lookup(tables, obj, rel, live, n_config_rels=NCR)
        flagged = flag_phase(
            tables, obj, rel, live, n_config_rels=NCR, island_is_host=True,
            prog=prog,
        )
        needs_host = st.needs_host.at[q].max(flagged)
        _ns, has_prog, pid, _flags = prog

        # instruction lanes (COMPUTED / TTU), exactly like check
        ipack = _isolate(tables["instr_pack"][pid]).reshape(F, K, 4)
        ik = jnp.where(has_prog[:, None], ipack[..., 0], 0)
        ir = ipack[..., 1]
        ir2 = ipack[..., 2]

        # full-CSR spans for slot 0 (the task's own row: plain-subject
        # emission + subject-set children) and the TTU rows
        rels = jnp.concatenate([rel[:, None], ir], axis=1)  # [F, S]
        spans = _multi_pair_key_probe(
            tables, "fsh", obj, rels, fsh_probes, n_vals=2
        )
        starts = spans[..., 0]
        row_len = jnp.where(starts < 0, 0, spans[..., 1] - starts)

        can_row = live & (depth >= 1)
        is_comp = (ik == INSTR_COMPUTED) & can_row[:, None]
        is_ttu = (ik == INSTR_TTU) & can_row[:, None]

        if has_delta:
            dirty_vals = _multi_pair_key_probe(
                tables, "dirty", obj, rels, DELTA_PROBES
            )
            row_dirty = (jnp.maximum(dirty_vals, 0) & DIRTY_FOR_EXPAND) != 0
            dirty = (can_row & row_dirty[:, 0]) | jnp.any(
                is_ttu & row_dirty[:, 1:], axis=1
            )
            needs_host = needs_host.at[q].max(
                jnp.where(dirty, CAUSE_DIRTY, 0).astype(jnp.int32)
            )

        counts = jnp.concatenate(
            [
                jnp.where(can_row, row_len[:, 0], 0)[:, None],
                jnp.where(is_comp, 1, jnp.where(is_ttu, row_len[:, 1:], 0)),
            ],
            axis=1,
        )
        slot_kind = jnp.concatenate(
            [
                jnp.zeros((F, 1), jnp.int32),
                jnp.where(is_comp, 1, jnp.where(is_ttu, 2, 0)),
            ],
            axis=1,
        )

        flat_counts = counts.reshape(-1)
        offsets = jnp.cumsum(flat_counts) - flat_counts
        total = offsets[-1] + flat_counts[-1]
        truncated = (offsets + flat_counts) > F
        seg_q = jnp.repeat(q, S, total_repeat_length=F * S)
        needs_host = needs_host.at[seg_q].max(
            jnp.where(
                truncated & (flat_counts > 0), CAUSE_FRONTIER_OVERFLOW, 0
            ).astype(jnp.int32)
        )

        seg, j = _seg_map(offsets, flat_counts, F)
        in_range = j < jnp.minimum(total, F)

        srcmat = jnp.stack(
            [
                jnp.broadcast_to(q[:, None], (F, S)),
                jnp.broadcast_to(obj[:, None], (F, S)),
                jnp.broadcast_to(depth[:, None], (F, S)),
                starts,
                slot_kind,
                jnp.concatenate(
                    [
                        jnp.zeros((F, 1), jnp.int32),
                        # instruction child relation: COMPUTED swaps to
                        # ir at the same depth, TTU children carry ir2
                        jnp.where(ik == INSTR_COMPUTED, ir, ir2),
                    ],
                    axis=1,
                ),
                offsets.reshape(F, S),
                jnp.zeros((F, S), jnp.int32),
            ],
            axis=-1,
        ).reshape(F * S, 8)
        src = _isolate(srcmat[seg])
        src_q = src[:, 0]
        src_obj = src[:, 1]
        src_depth = src[:, 2]
        src_start = src[:, 3]
        src_kind = src[:, 4]
        src_crel = src[:, 5]
        within = j - src[:, 6]

        e = jnp.clip(src_start + within, 0, max(n_edges - 1, 0))
        if n_edges:
            ep = _isolate(tables["fe_pack"][e])  # (skind, sa, sb, 0)
            e_skind, e_sa, e_sb = ep[:, 0], ep[:, 1], ep[:, 2]
        else:
            e_skind = jnp.zeros(F, jnp.int32)
            e_sa = jnp.zeros(F, jnp.int32)
            e_sb = jnp.zeros(F, jnp.int32)

        is_row = src_kind == 0
        is_c = src_kind == 1
        is_t = src_kind == 2

        # result emission: plain-subject edges of the task's own row (the
        # batched analog of the direct probe hitting at depth >= 1)
        emit = in_range & is_row & (e_skind == 0)
        alloc = _bump_emit(src_q, emit, st.res_count, F, B)
        res_over = emit & (alloc >= R)
        needs_host = needs_host.at[src_q].max(
            jnp.where(res_over, CAUSE_FRONTIER_OVERFLOW, 0).astype(jnp.int32)
        )
        emit = emit & ~res_over
        dest = jnp.where(emit, src_q * R + alloc, B * R)
        res_sub = st.res_sub.at[dest].set(e_sa, mode="drop")
        res_count = st.res_count.at[src_q].add(emit.astype(jnp.int32))

        # children: subject-set edges (slot 0: their own sb relation,
        # wildcard-filtered like check; TTU rows: the instruction's
        # rel2) + COMPUTED relation swaps at the same depth
        child_obj = jnp.where(is_c, src_obj, e_sa)
        child_rel = jnp.where(is_row, e_sb, src_crel)
        child_depth = jnp.where(is_c, src_depth, src_depth - 1)
        cond = jnp.where(
            is_row,
            (e_skind == 1) & (e_sb != wildcard_rel),
            is_c | (e_skind == 1),
        )
        children = Expansion(
            q=src_q, ctx=src_q, obj=child_obj, rel=child_rel,
            depth=child_depth,
            valid=in_range & cond & (child_depth >= 1),
        )
        nt_q, _nt_ctx, nt_obj, nt_rel, nt_depth, n_new, overflow_q = (
            dedupe_phase(children, F, B)
        )
        needs_host = jnp.maximum(needs_host, overflow_q)
        stats = update_launch_stats(
            st.stats,
            st.n_tasks,
            (live & (depth >= 0)).sum(),
            emit.sum(),
            children.valid.sum(),
            n_new,
        )
        return _SubState(
            nt_q, nt_obj, nt_rel, nt_depth, n_new,
            res_sub, res_count, needs_host, st.step + 1, stats,
        )

    def cond_fn(st: _SubState):
        return (
            (st.step < max_steps)
            & (st.n_tasks > 0)
            & ~jnp.all(st.needs_host > 0)
        )

    final = bounded_loop(cond_fn, step_fn, init, max_steps)
    exhausted = (final.step >= max_steps) & (final.n_tasks > 0)
    live = jnp.arange(F, dtype=jnp.int32) < final.n_tasks
    needs_host = final.needs_host.at[final.t_q].max(
        jnp.where(exhausted & live, CAUSE_STEP_EXHAUSTED, 0).astype(jnp.int32)
    )
    return final.res_sub, final.res_count, needs_host, final.stats


@functools.partial(
    jax.jit, static_argnames=_SUBJECTS_STATICS + ("pool_cap",)
)
def list_subjects_kernel_packed(
    tables: dict,
    qpack: jnp.ndarray,  # [4, B] int32: obj, rel, depth, valid
    *,
    K: int,
    fsh_probes: int,
    max_steps: int,
    wildcard_rel: int,
    n_config_rels: int,
    frontier_cap: int,
    result_cap: int,
    pool_cap: int,
    has_delta: bool,
):
    """Packed twin of list_objects_kernel_packed for the subjects leg:
    [ offsets (B+1) | needs_host (B) | stats (N_LAUNCH_STATS) |
    pool (pool_cap) ] of plain subject ids (revisit duplicates possible;
    host dedupes)."""
    B = qpack.shape[1]
    R = result_cap
    res_sub, res_count, needs_host, stats = _list_subjects_impl(
        tables,
        qpack[0], qpack[1], qpack[2], qpack[3].astype(bool),
        K=K, fsh_probes=fsh_probes, max_steps=max_steps,
        wildcard_rel=wildcard_rel, n_config_rels=n_config_rels,
        frontier_cap=frontier_cap, result_cap=result_cap,
        has_delta=has_delta,
    )
    counts = jnp.clip(res_count, 0, R)
    offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts).astype(jnp.int32)]
    )
    j = jnp.arange(pool_cap, dtype=jnp.int32)
    seg = jnp.searchsorted(offs[1:], j, side="right").astype(jnp.int32)
    seg_c = jnp.clip(seg, 0, B - 1)
    within = j - offs[seg_c]
    valid = (j < offs[B]) & (seg < B)
    src = jnp.clip(seg_c * R + within, 0, B * R - 1)
    pool = jnp.where(valid, res_sub[src], EMPTY)
    needs_host = jnp.maximum(
        needs_host,
        jnp.where(
            (offs[1:] > pool_cap) & (counts > 0), CAUSE_FRONTIER_OVERFLOW, 0
        ).astype(jnp.int32),
    )
    offs = jnp.minimum(offs, pool_cap)
    return jnp.concatenate([offs, needs_host, stats.astype(jnp.int32), pool])


def decode_pool_slice(pool: np.ndarray, lo: int, hi: int) -> list[int]:
    """Ordered, deduplicated ids from one query's pool span (a node
    revisited at a deeper depth in a later step re-emits)."""
    seen: set[int] = set()
    out: list[int] = []
    for v in pool[lo:hi].tolist():
        if v in seen:
            continue
        seen.add(v)
        out.append(v)
    return out
