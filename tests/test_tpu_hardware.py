"""TPU-hardware test tier (pytest marker `tpu`): runs the differential
fixture sets on the REAL attached backend via tools/tpu_test_tier.py in
a subprocess — a wedged TPU tunnel (observed repeatedly on this machine)
times out and SKIPS instead of hanging the suite.

Round-1 VERDICT item 2: before this tier existed, zero correctness
evidence had ever executed on TPU hardware."""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PROBE = (
    "import jax; d = jax.devices(); print('PROBE', d[0].platform, flush=True)"
)


def _tpu_available(timeout_s: float = 60.0) -> bool:
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired:
        return False
    for line in out.stdout.splitlines():
        if line.startswith("PROBE "):
            return line.split()[1] not in ("cpu",)
    return False


@pytest.mark.tpu
def test_tpu_differential_tier():
    if os.environ.get("KETO_TPU_TESTS", "") not in ("1", "true"):
        pytest.skip("set KETO_TPU_TESTS=1 to run the TPU-hardware tier")
    if not _tpu_available():
        pytest.skip("no healthy TPU backend (probe timed out or cpu-only)")
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "tpu_test_tier.py")],
        capture_output=True, text=True, timeout=1200, env=env, cwd=_REPO,
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    assert lines, f"no output from TPU tier: {out.stderr[-2000:]}"
    summary = json.loads(lines[-1])
    assert out.returncode == 0, (summary, out.stderr[-2000:])
    assert summary.get("failures") == 0, summary
    assert summary.get("cases", 0) >= 150, summary
