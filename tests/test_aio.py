"""asyncio read plane (api/aio_server.py): behavior parity with the
threaded gRPC read surface, exercised through the SAME ReadClient the
sync-plane tests use — the wire contract is identical, only the server
architecture differs (every RPC a coroutine, in-loop batching)."""

import threading
import time

import grpc
import pytest

from keto_tpu.api import ReadClient, WriteClient, open_channel
from keto_tpu.api.daemon import Daemon
from keto_tpu.config import Config
from keto_tpu.ketoapi import RelationQuery, RelationTuple, SubjectSet
from keto_tpu.registry import Registry

NAMESPACES = [
    {
        "name": "videos",
        "relations": [
            {"name": "owner"},
            {
                "name": "view",
                "rewrite": {
                    "operation": "or",
                    "children": [
                        {"type": "computed_subject_set", "relation": "owner"}
                    ],
                },
            },
        ],
    },
]


@pytest.fixture(scope="module")
def daemon():
    cfg = Config(
        {
            "dsn": "memory",
            "check": {"engine": "tpu"},
            "serve": {
                "read": {
                    "host": "127.0.0.1", "port": 0,
                    "grpc": {"host": "127.0.0.1", "port": 0, "aio": True},
                },
                "write": {"host": "127.0.0.1", "port": 0},
                "metrics": {"host": "127.0.0.1", "port": 0},
            },
            "namespaces": NAMESPACES,
        }
    )
    d = Daemon(Registry(cfg))
    d.start()
    yield d
    d.stop()


@pytest.fixture(scope="module")
def clients(daemon):
    rc = ReadClient(open_channel(f"127.0.0.1:{daemon.read_grpc_port}"))
    wc = WriteClient(open_channel(f"127.0.0.1:{daemon.write_port}"))
    yield rc, wc
    rc.close()
    wc.close()


def t(s: str) -> RelationTuple:
    return RelationTuple.from_string(s)


class TestAioReadPlane:
    def test_check_and_rewrite(self, clients):
        rc, wc = clients
        wc.transact(insert=[t("videos:/a#owner@alice")])
        assert rc.check(t("videos:/a#owner@alice"))
        assert rc.check(t("videos:/a#view@alice"))  # computed rewrite
        assert not rc.check(t("videos:/a#owner@bob"))

    def test_concurrent_checks_batch(self, clients):
        rc, wc = clients
        wc.transact(insert=[t(f"videos:/c{i}#owner@u{i}") for i in range(16)])
        results = {}
        addr_clients = []

        def worker(i):
            results[i] = rc.check(t(f"videos:/c{i}#owner@u{i}"))

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(16)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert all(results[i] for i in range(16))
        for c in addr_clients:
            c.close()

    def test_batch_check_rpc(self, clients):
        """The batch extension rides the aio plane too (delegated to the
        blocking executor like Expand — the batch already did the
        coalescing client-side)."""
        rc, wc = clients
        wc.transact(insert=[t("videos:/b#owner@alice")])
        results = rc.check_batch(
            [t("videos:/b#owner@alice"), t("videos:/b#owner@bob")]
        )
        assert [r[0] for r in results] == [True, False]
        assert all(r[1] == "" for r in results)

    def test_expand(self, clients):
        rc, wc = clients
        wc.transact(insert=[t("videos:/e#owner@erin")])
        tree = rc.expand(SubjectSet("videos", "/e", "owner"))
        assert tree is not None

    def test_list_relation_tuples(self, clients):
        rc, wc = clients
        wc.transact(insert=[t("videos:/l#owner@lee")])
        resp = rc.list_relation_tuples(
            RelationQuery(namespace="videos", object="/l")
        )
        assert any(
            x.subject_id == "lee" for x in resp.relation_tuples
        )

    def test_version_and_health(self, clients):
        rc, _ = clients
        assert rc.get_version()
        assert rc.health() == "SERVING"

    def test_unknown_namespace_is_grpc_error(self, clients):
        rc, _ = clients
        with pytest.raises(grpc.RpcError) as err:
            rc.check(t("nope:/x#owner@alice"))
        assert err.value.code() in (
            grpc.StatusCode.INVALID_ARGUMENT, grpc.StatusCode.NOT_FOUND
        )

    def test_health_watch_stream(self, daemon):
        from keto_tpu.api.descriptors import HEALTH_SERVICE, pb

        chan = open_channel(f"127.0.0.1:{daemon.read_grpc_port}")
        watch = chan.unary_stream(
            f"/{HEALTH_SERVICE}/Watch",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.HealthCheckResponse.FromString,
        )
        stream = watch(pb.HealthCheckRequest(), timeout=10)
        first = next(stream)
        assert first.status == 1  # SERVING
        stream.cancel()
        chan.close()

    def test_read_your_writes(self, clients):
        rc, wc = clients
        for i in range(3):
            wc.transact(insert=[t(f"videos:/w{i}#owner@w{i}")])
            assert rc.check(t(f"videos:/w{i}#owner@w{i}"))


def _make_daemon(engine: str):
    cfg = Config(
        {
            "dsn": "memory",
            "check": {"engine": engine},
            "serve": {
                "read": {
                    "host": "127.0.0.1", "port": 0,
                    "grpc": {"host": "127.0.0.1", "port": 0, "aio": True},
                },
                "write": {"host": "127.0.0.1", "port": 0},
                "metrics": {"host": "127.0.0.1", "port": 0},
            },
            "namespaces": NAMESPACES,
        }
    )
    d = Daemon(Registry(cfg))
    d.start()
    return d


class TestAioLifecycle:
    def test_host_engine_fallback(self):
        """check.engine=host has no split-phase surface; the aio batcher
        must fall back to whole-batch evaluation (the threaded batcher's
        getattr guard, mirrored)."""
        d = _make_daemon("host")
        try:
            rc = ReadClient(open_channel(f"127.0.0.1:{d.read_grpc_port}"))
            wc = WriteClient(open_channel(f"127.0.0.1:{d.write_port}"))
            wc.transact(insert=[t("videos:/h#owner@hana")])
            assert rc.check(t("videos:/h#owner@hana"))
            assert not rc.check(t("videos:/h#owner@hugo"))
            rc.close(); wc.close()
        finally:
            d.stop()

    def test_stop_is_prompt(self):
        """Shutdown must complete within the grace budget — the loop has
        to outlive the server so the batcher/executors actually close
        (the run_until_complete(serve) shape raced this and burned the
        full stop timeout on every shutdown)."""
        d = _make_daemon("tpu")
        t0 = time.monotonic()
        d.stop()
        assert time.monotonic() - t0 < 8.0


class TestServedMergeChurn:
    def test_incremental_merge_under_live_traffic(self):
        """Write churn past the delta-overlay capacity while checks
        stream through the served aio plane: the merge happens inside
        the serving stack and read-your-writes holds across it."""
        from keto_tpu.engine.delta import DELTA_COMPACT_THRESHOLD

        d = _make_daemon("tpu")
        try:
            rc = ReadClient(open_channel(f"127.0.0.1:{d.read_grpc_port}"))
            wc = WriteClient(open_channel(f"127.0.0.1:{d.write_port}"))
            wc.transact(insert=[t("videos:/m0#owner@m0")])
            assert rc.check(t("videos:/m0#owner@m0"))

            # one oversized burst (the log dedupes, so distinct tuples)
            n = DELTA_COMPACT_THRESHOLD + 16
            batch = [t(f"videos:/mb{i}#owner@mu{i}") for i in range(n)]
            for i in range(0, n, 512):
                wc.transact(insert=batch[i : i + 512])
            # served checks observe the merged base immediately
            assert rc.check(t(f"videos:/mb{n-1}#owner@mu{n-1}"))
            assert rc.check(t("videos:/m0#owner@m0"))  # old base intact
            assert not rc.check(t("videos:/mb3#owner@mu4"))
            eng = d.registry.check_engine()
            assert eng.stats.get("incremental_merges", 0) >= 1
            assert eng.stats["snapshot_builds"] == 1
            rc.close(); wc.close()
        finally:
            d.stop()
