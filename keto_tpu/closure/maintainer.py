"""ClosureMaintainer: the Leopard index's freshness loop.

One background thread per process (registry singleton, daemon-managed)
keeps every BUILT engine's closure index (engine/closure.py) current:

  - a Watch-hub subscription per network id tails the changelog (PR 2's
    versioned feed — the same substrate the check cache and replica
    views ride); each WatchEvent's changes are folded into the index's
    dirty-node overlay (transitive-ancestor marking), advancing its
    synced version. A RESET event (ring overflow / changelog truncation)
    marks the index wholly stale — incremental maintenance lost the
    thread, so the next pass re-powers.
  - per pass, every index that needs (re)building — first touch, base
    snapshot swapped by a compaction, dirty-overlay overflow, RESET —
    is re-powered OFF the request path via engine.closure_ensure_built.

Correctness NEVER depends on this thread: every closure answer is
version-gated at submit (index synced_version >= the serving state's
covered_version, engine/tpu_engine.py _closure_gate), so a paused,
slow, or dead maintainer degrades deep-check latency back to the BFS
kernel and nothing else. `hold()`/`release()` exist precisely to prove
that in tests and smokes — a held maintainer is the forced-lag fault
(the `tools/replica_smoke.py` held-tailer trick, applied here).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

logger = logging.getLogger("keto_tpu")

DEFAULT_POLL_INTERVAL = 0.25


class ClosureMaintainer:
    def __init__(self, registry, poll_interval: float = DEFAULT_POLL_INTERVAL):
        self.registry = registry
        self.poll_interval = max(float(poll_interval), 0.01)
        self._subs: dict[str, object] = {}
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._held = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        self.stats = {"passes": 0, "events": 0, "rebuilds": 0, "resets": 0}

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        with self._mu:
            if self._thread is not None:
                return
            self._stopped.clear()
            # commit-listener wakeup: writes poke the loop immediately
            # instead of waiting out the poll interval (flag flip only —
            # the listener runs on the writer thread). Registered ONCE
            # per maintainer: the hub has no remove API, and a
            # start/stop/start cycle must not accumulate listeners.
            if not getattr(self, "_listener_registered", False):
                self.registry.watch_hub().add_commit_listener(
                    self._on_commit
                )
                self._listener_registered = True
            self._thread = threading.Thread(
                target=self._loop, name="keto-closure-maintainer", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._mu:
            thread, self._thread = self._thread, None
        self._stopped.set()
        self._wake.set()
        if thread is not None:
            thread.join(timeout=5)
        for sub in self._subs.values():
            try:
                sub.close()
            except Exception:  # noqa: BLE001 — teardown must complete
                logger.debug("closure subscription close failed",
                             exc_info=True)
        self._subs.clear()

    def hold(self) -> None:
        """Freeze maintenance (tests/smokes force the lagging-index
        regime: fallbacks must stay correct while held)."""
        self._held.set()

    def release(self) -> None:
        self._held.clear()
        self._wake.set()

    def _on_commit(self, nid: str) -> None:
        self._wake.set()

    # -- the loop -------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stopped.is_set():
            self._wake.wait(self.poll_interval)
            self._wake.clear()
            if self._stopped.is_set():
                return
            if self._held.is_set():
                continue
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the freshness loop must
                # never die; the version gate keeps serving correct and
                # the next pass retries
                logger.debug("closure maintenance pass failed", exc_info=True)

    def step(self) -> int:
        """One maintenance pass over every built engine: drain pending
        watch events into the dirty overlays, then (re)build whatever
        needs powering. Returns the number of events applied (tests and
        the correctness smoke call this directly for deterministic
        interleaving)."""
        applied = 0
        self.stats["passes"] += 1
        for nid, engine in self.registry.built_engines().items():
            index_fn = getattr(engine, "closure_index", None)
            if index_fn is None or not getattr(engine, "closure_enabled", False):
                continue
            idx = index_fn()
            # ensure BEFORE draining events: ensure_for advances the op
            # encoder to the engine's current overlay view and its
            # catch_up marks under it — an event drained first would
            # apply (and advance synced past) ops the STALE encoder
            # cannot encode, permanently skipping their marks. It is
            # idempotent-cheap when current (one store version read),
            # re-powers after compactions/staleness, runs the dirty
            # refresh, and folds changes the event path missed
            # (out-of-process writers).
            before = idx.stats["builds"]
            try:
                engine.closure_ensure_built()
            except Exception:  # noqa: BLE001 — a failing powering must
                # not stop maintenance of other engines
                logger.warning(
                    "closure build failed for nid=%s", nid, exc_info=True
                )
                continue
            if idx.stats["builds"] != before:
                self.stats["rebuilds"] += 1
            applied += self._drain_events(nid, idx)
        return applied

    def _drain_events(self, nid: str, idx) -> int:
        sub = self._subs.get(nid)
        if sub is None:
            hub = self.registry.watch_hub()
            try:
                sub = hub.subscribe(nid)
            except RuntimeError:
                return 0  # hub stopped: daemon is shutting down
            self._subs[nid] = sub
        applied = 0
        while True:
            try:
                event = sub.get_nowait()
            except Exception:  # noqa: BLE001 — a failed resume is a
                # missed optimization, not an error (catch_up covers it)
                break
            if event is None:
                break
            if event.is_reset:
                # the changelog gap is unrecoverable incrementally: the
                # next build pass re-powers from the store
                idx.mark_stale()
                self.stats["resets"] += 1
                continue
            idx.apply_changes(event.changes, event.version)
            applied += len(event.changes)
        self.stats["events"] += applied
        return applied
