"""Append the keto_tpu_watch.proto descriptor to keto_descriptors.binpb.

The build image ships no protoc, so the watch extension's
FileDescriptorProto is constructed programmatically here (field-for-field
mirror of keto_tpu/api/protos/keto_tpu_watch.proto) and appended to the
checked-in descriptor set — idempotently: an existing entry with the same
file name is replaced, so the tool can re-run after edits (the
gen_reverse_descriptor.py pattern). Run from the repo root:

    python tools/gen_watch_descriptor.py

api/descriptors.py then materializes the message classes from the same
descriptor pool as every other message — no generated *_pb2.py code.
"""

from __future__ import annotations

import pathlib
import sys

from google.protobuf import descriptor_pb2

_REPO = pathlib.Path(__file__).resolve().parent.parent
_BINPB = _REPO / "keto_tpu" / "api" / "protos" / "keto_descriptors.binpb"

_STR = descriptor_pb2.FieldDescriptorProto.TYPE_STRING
_MSG = descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
_OPT = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
_REP = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED

_TUPLE = ".ory.keto.relation_tuples.v1alpha2.RelationTuple"


def _message(fd, name: str, fields):
    m = fd.message_type.add()
    m.name = name
    for number, (fname, ftype, label, type_name) in enumerate(fields, 1):
        f = m.field.add()
        f.name = fname
        f.number = number
        f.type = ftype
        f.label = label
        if type_name:
            f.type_name = type_name
    return m


def build_file() -> descriptor_pb2.FileDescriptorProto:
    fd = descriptor_pb2.FileDescriptorProto()
    fd.name = "keto_tpu_watch.proto"
    fd.package = "keto_tpu.watch.v1"
    fd.syntax = "proto3"
    fd.dependency.append("keto.proto")
    _message(fd, "WatchRequest", [
        ("snaptoken", _STR, _OPT, None),
        ("namespace", _STR, _OPT, None),
    ])
    _message(fd, "WatchChange", [
        ("action", _STR, _OPT, None),
        ("relation_tuple", _MSG, _OPT, _TUPLE),
    ])
    _message(fd, "WatchResponse", [
        ("event_type", _STR, _OPT, None),
        ("snaptoken", _STR, _OPT, None),
        ("changes", _MSG, _REP, ".keto_tpu.watch.v1.WatchChange"),
    ])
    svc = fd.service.add()
    svc.name = "WatchService"
    m = svc.method.add()
    m.name = "Watch"
    m.input_type = ".keto_tpu.watch.v1.WatchRequest"
    m.output_type = ".keto_tpu.watch.v1.WatchResponse"
    m.server_streaming = True
    return fd


def main() -> int:
    fds = descriptor_pb2.FileDescriptorSet()
    fds.ParseFromString(_BINPB.read_bytes())
    new = build_file()
    kept = [f for f in fds.file if f.name != new.name]
    del fds.file[:]
    fds.file.extend(kept)
    fds.file.append(new)
    _BINPB.write_bytes(fds.SerializeToString())
    print(f"wrote {new.name} into {_BINPB} ({len(fds.file)} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
