"""Open-loop load generator for the serving plane.

The r04 served numbers were CLOSED-loop: N clients each waiting for
their previous response, so offered load is capped at N / latency and a
slow server hides its own queueing (coordinated omission). This drives
the read plane OPEN-loop: requests are scheduled on a fixed timeline at
`--rate` regardless of completions, so latency-under-load and the
saturation knee are visible.

Three request shapes:
  --mode single   one check per RPC (the v1alpha2 parity surface)
  --mode batch    one BatchCheck RPC per tick carrying --batch checks
                  (the keto_tpu extension; offered checks/s =
                  rate * batch)
  --mode filter   one BatchFilter RPC per tick carrying a
                  --filter-objects candidate column for one subject
                  (the bulk-ACL-filtering workload; offered objects/s =
                  rate * filter-objects). --filter-hit-rate biases how
                  many candidates come from the subject's own folder
                  (the rest are random documents), so saturation curves
                  can sweep sparse vs dense result shapes.

    python tools/load_gen.py --addr 127.0.0.1:4466 --rate 200 \
        --seconds 10 --mode batch --batch 512

Prints one JSON line: offered vs achieved rate, completion latency
percentiles (measured from SCHEDULED send time — queueing delay from a
saturated server counts, as it should), error/timeout counts.

Saturation curves (`--curve`): a stepped offered-QPS ladder — each step
runs the open loop at one offered rate for `--seconds`, recording
achieved QPS and p50/p95/p99 per step, so the knee where achieved
detaches from offered (and latency departs) is measurable in ONE
committed artifact instead of hand-run points:

    python tools/load_gen.py --addr 127.0.0.1:4466 \
        --curve 200,400,800,1600 --seconds 5 --record CURVE.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_filter_workload(
    objects_per_request: int, hit_rate: float, n_requests: int = 64,
    seed: int = 9,
):
    """(subject, candidate list) request pool for `--mode filter`,
    derived from the bench dataset's cat-videos topology: the subject is
    a folder owner, `hit_rate` of the candidates come from folders they
    own (reachable via the parent TTU) and the rest are random other
    documents — the sparse/dense search-result-shape knob."""
    import bench

    _, tuples, _ = bench.build_dataset()
    rng = random.Random(seed)
    owner_folders: dict[str, list[str]] = {}
    all_files: list[str] = []
    for t in tuples:
        if t.relation == "owner" and t.subject_id and "/" not in t.object[1:]:
            owner_folders.setdefault(t.subject_id, []).append(t.object)
        elif t.relation == "parent":
            all_files.append(t.object)
    owners = [s for s, folders in owner_folders.items() if folders]
    pool = []
    for _ in range(n_requests):
        sub = owners[rng.randrange(len(owners))]
        owned_prefixes = tuple(p + "/" for p in owner_folders[sub])
        owned = [
            f for f in all_files if f.startswith(owned_prefixes)
        ] or all_files
        cands = [
            (
                owned[rng.randrange(len(owned))]
                if rng.random() < hit_rate
                else all_files[rng.randrange(len(all_files))]
            )
            for _ in range(objects_per_request)
        ]
        pool.append((sub, cands))
    return pool


def load_profile(path: str):
    """(queries, weights) from a captured workload profile
    (`keto-tpu admin capture` / GET /admin/workload): the profile's
    check-key popularity histogram becomes a weighted query pool, so a
    replay drives the server with the MEASURED key skew instead of a
    uniform synthetic mix — the replay half of the capture/replay
    loop."""
    from keto_tpu.ketoapi import RelationTuple

    with open(path) as f:
        profile = json.load(f)
    if profile.get("schema") != "keto-tpu-workload-profile/1":
        raise SystemExit(
            f"{path} is not a workload profile "
            f"(schema={profile.get('schema')!r})"
        )
    queries: list = []
    weights: list[int] = []
    for e in (profile.get("key_popularity") or {}).get("check") or []:
        try:
            queries.append(RelationTuple.from_string(e["key"]))
        except Exception:
            continue  # a malformed key skips one entry, never the replay
        weights.append(max(int(e.get("count", 1)), 1))
    if not queries:
        raise SystemExit(f"{path} carries no replayable check keys")
    return queries, weights


def _make_sampler(rng, qn: int, weights=None):
    """Index sampler over the query pool: uniform without weights,
    popularity-proportional (cumulative + bisect, O(log n) per draw)
    when a profile supplied them."""
    if not weights:
        return lambda: rng.randrange(qn)
    import bisect

    cum: list[int] = []
    acc = 0
    for w in weights[:qn]:
        acc += w
        cum.append(acc)
    total = acc

    def pick() -> int:
        return min(
            bisect.bisect_right(cum, rng.random() * total), qn - 1
        )

    return pick


def run_step(
    clients, queries, rate: float, seconds: float,
    mode: str = "single", batch: int = 512, timeout: float = 30.0,
    workers: int = 64, filter_queries=None, weights=None,
) -> dict:
    """One open-loop step at a fixed offered rate; returns the result
    record (achieved QPS, scheduled-send latency percentiles, errors,
    shed ticks). `clients` is a pool of ReadClients reused across steps
    so channel setup never lands inside a timed window. `weights`
    (from --profile) makes query sampling popularity-proportional."""
    rng = random.Random(0)
    qn = len(queries) if queries else 0
    pick = _make_sampler(rng, qn, weights) if qn else None
    lock = threading.Lock()
    lat: list[float] = []
    errors = [0]
    checks_done = [0]
    shed = [0]
    inflight = threading.Semaphore(workers)

    def fire(scheduled: float, client) -> None:
        try:
            if mode == "single":
                q = queries[pick()]
                client.check(q, timeout=timeout)
                n = 1
            elif mode == "filter":
                sub, cands = filter_queries[
                    rng.randrange(len(filter_queries))
                ]
                client.filter("videos", "view", sub, cands, timeout=timeout)
                n = len(cands)
            elif weights:
                # profile replay: each batch item drawn by popularity
                # (a contiguous slice would flatten the skew)
                qs = [queries[pick()] for _ in range(batch)]
                client.check_batch(qs, timeout=timeout)
                n = batch
            else:
                start = rng.randrange(qn)
                qs = [queries[(start + j) % qn] for j in range(batch)]
                client.check_batch(qs, timeout=timeout)
                n = batch
            done = time.perf_counter()
            with lock:
                lat.append(done - scheduled)
                checks_done[0] += n
        except Exception:
            with lock:
                errors[0] += 1
        finally:
            inflight.release()

    n_ticks = int(rate * seconds)
    interval = 1.0 / rate
    t0 = time.perf_counter()
    threads: list[threading.Thread] = []
    for i in range(n_ticks):
        scheduled = t0 + i * interval
        now = time.perf_counter()
        if scheduled > now:
            time.sleep(scheduled - now)
        if not inflight.acquire(blocking=False):
            with lock:
                shed[0] += 1
            continue
        th = threading.Thread(
            target=fire, args=(scheduled, clients[i % len(clients)]),
            daemon=True,
        )
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout + 5)
    wall = time.perf_counter() - t0

    import numpy as np

    per_tick = 1
    if mode == "batch":
        per_tick = batch
    elif mode == "filter":
        per_tick = len(filter_queries[0][1]) if filter_queries else 0
    out = {
        "mode": mode,
        "offered_rps": rate,
        "offered_checks_per_s": rate * per_tick,
        "achieved_checks_per_s": round(checks_done[0] / wall, 1),
        "completed_rpcs": len(lat),
        "errors": errors[0],
        "shed_ticks": shed[0],
        "wall_s": round(wall, 2),
    }
    if lat:
        a = np.array(lat) * 1e3
        out.update({
            "lat_p50_ms": round(float(np.percentile(a, 50)), 2),
            "lat_p95_ms": round(float(np.percentile(a, 95)), 2),
            "lat_p99_ms": round(float(np.percentile(a, 99)), 2),
        })
    return out


def run_curve(
    addr: str, rates, seconds: float, mode: str = "single",
    batch: int = 512, timeout: float = 30.0, workers: int = 64,
    queries=None, n_clients: int = 8, filter_queries=None, weights=None,
) -> dict:
    """The stepped saturation ladder as a callable (replica_smoke's
    committed-artifact path imports this): one open-loop step per
    offered rate, one shared client pool, results under "curve"."""
    from keto_tpu.api import ReadClient, open_channel

    if queries is None and mode != "filter":
        import bench

        _, _, queries = bench.build_dataset()
    clients = [ReadClient(open_channel(addr)) for _ in range(n_clients)]
    try:
        steps = [
            run_step(
                clients, queries, rate, seconds,
                mode=mode, batch=batch, timeout=timeout, workers=workers,
                filter_queries=filter_queries, weights=weights,
            )
            for rate in rates
        ]
    finally:
        for c in clients:
            c.close()
    peak = max(
        (s["achieved_checks_per_s"] for s in steps), default=0.0
    )
    return {
        "mode": mode,
        "step_seconds": seconds,
        "curve": steps,
        "peak_achieved_checks_per_s": peak,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--addr", default="127.0.0.1:4466")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="request ticks per second (open-loop schedule)")
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument(
        "--mode", choices=("single", "batch", "filter"), default="single",
        help="filter = one BatchFilter RPC per tick (--workload filter)",
    )
    # alias so `--workload filter` reads naturally beside --mode
    ap.add_argument("--workload", choices=("single", "batch", "filter"),
                    default=None, help="alias for --mode")
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--filter-objects", type=int, default=1024,
                    help="candidate-list size per filter RPC")
    ap.add_argument("--filter-hit-rate", type=float, default=0.1,
                    help="fraction of candidates drawn from the "
                         "subject's own folders (rest are random)")
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--workers", type=int, default=64,
                    help="in-flight cap (past it, ticks count as shed)")
    ap.add_argument("--curve", default=None, metavar="R1,R2,...",
                    help="stepped open-loop mode: run --seconds at each "
                         "offered rate in the comma-separated ladder and "
                         "emit per-step achieved QPS + p50/p95/p99 — the "
                         "saturation-curve artifact")
    ap.add_argument("--queries", default=None,
                    help="JSON file of relation tuples; default: the "
                         "bench dataset's query mix")
    ap.add_argument("--profile", default=None, metavar="PROFILE_JSON",
                    help="replay a captured workload profile (keto-tpu "
                         "admin capture): the check-key popularity "
                         "histogram becomes a WEIGHTED query pool, so "
                         "the drive reproduces the measured skew; "
                         "overrides --queries")
    ap.add_argument("--record", default=None, metavar="OUT_JSON",
                    help="also write the result record to this file — "
                         "the committed-artifact mode (saturation curves "
                         "land in the repo, not just a terminal scroll)")
    args = ap.parse_args()

    from keto_tpu.api import ReadClient, open_channel
    from keto_tpu.ketoapi import RelationTuple

    if args.workload is not None:
        args.mode = args.workload
    filter_queries = None
    weights = None
    if args.mode == "filter":
        filter_queries = build_filter_workload(
            args.filter_objects, args.filter_hit_rate
        )
        queries = None
    elif args.profile:
        queries, weights = load_profile(args.profile)
    elif args.queries:
        with open(args.queries) as f:
            queries = [RelationTuple.from_dict(d) for d in json.load(f)]
    else:
        import bench

        queries = None
        if args.curve is None:
            _, _, queries = bench.build_dataset()

    if args.curve is not None:
        rates = [float(r) for r in args.curve.split(",") if r.strip()]
        out = run_curve(
            args.addr, rates, args.seconds, mode=args.mode,
            batch=args.batch, timeout=args.timeout, workers=args.workers,
            queries=queries, filter_queries=filter_queries,
            weights=weights,
        )
    else:
        # a small client pool: gRPC channels multiplex, but one channel's
        # Python-side completion queue serializes; a handful spreads it
        clients = [ReadClient(open_channel(args.addr)) for _ in range(8)]
        try:
            out = run_step(
                clients, queries, args.rate, args.seconds,
                mode=args.mode, batch=args.batch, timeout=args.timeout,
                workers=args.workers, filter_queries=filter_queries,
                weights=weights,
            )
        finally:
            for c in clients:
                c.close()
    if args.mode == "filter":
        out["filter_objects"] = args.filter_objects
        out["filter_hit_rate"] = args.filter_hit_rate
    print(json.dumps(out))
    if args.record:
        with open(args.record, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
