"""Differential tests: the batched BFS kernel against the exact host
reference engine, on the ported fixture sets and randomized graphs.
Runs on the virtual CPU backend (conftest.py); the same code path runs
on TPU."""

import os
import random

import pytest

from keto_tpu.config import Config
from keto_tpu.engine import Membership, ReferenceEngine
from keto_tpu.engine.snapshot import build_snapshot
from keto_tpu.engine.tpu_engine import TPUCheckEngine
from keto_tpu.ketoapi import RelationTuple, SubjectSet
from keto_tpu.namespace import Namespace
from keto_tpu.namespace.ast import (
    ComputedSubjectSet,
    Relation,
    SubjectSetRewrite,
    TupleToSubjectSet,
)
from keto_tpu.storage import MemoryManager

from test_reference_engine import (
    REWRITE_CASES,
    REWRITE_NAMESPACES,
    REWRITE_TUPLES,
)


def make_tpu_engine(namespaces, tuples, max_depth=5):
    cfg = Config({"limit": {"max_read_depth": max_depth}})
    cfg.set_namespaces(namespaces)
    m = MemoryManager()
    m.write_relation_tuples([RelationTuple.from_string(s) for s in tuples])
    return TPUCheckEngine(m, cfg)


@pytest.fixture(scope="module")
def rewrite_tpu_engine():
    # one snapshot build + kernel compile for all 20 fixture cases
    return make_tpu_engine(REWRITE_NAMESPACES, REWRITE_TUPLES, max_depth=100)


class TestSnapshot:
    def test_build_and_encode(self):
        tuples = [
            RelationTuple.from_string("n:o#r@u"),
            RelationTuple.from_string("n:o#r@(n:o2#r2)"),
        ]
        snap = build_snapshot(tuples, [Namespace(name="n")])
        assert snap.n_tuples == 2
        node = snap.encode_node("n", "o", "r")
        assert node is not None
        assert snap.encode_node("missing", "o", "r") is None
        assert snap.encode_subject(tuples[0]) == (0, snap.subj_ids["u"], 0)
        skind, sa, sb = snap.encode_subject(tuples[1])
        assert skind == 1

    def test_hash_table_holds_all_edges(self):
        # build a snapshot with enough edges to force collisions
        tuples = [
            RelationTuple.from_string(f"n:o{i % 97}#r{i % 11}@u{i}")
            for i in range(2000)
        ]
        snap = build_snapshot(tuples, [])
        assert (snap.dh_val != -1).sum() == 2000


class TestKernelDifferential:
    @pytest.mark.skipif(
        not os.path.isdir(
            "/root/reference/contrib/cat-videos-example/relation-tuples"
        ),
        reason="reference checkout with the cat-videos fixture not present",
    )
    def test_cat_videos(self):
        import glob
        import json

        tuples = []
        for f in sorted(
            glob.glob(
                "/root/reference/contrib/cat-videos-example/relation-tuples/*.json"
            )
        ):
            d = json.load(open(f))
            d.pop("$schema", None)
            tuples.append(str(RelationTuple.from_dict(d)))
        e = make_tpu_engine([Namespace(name="videos")], tuples)
        queries = [
            "videos:/cats/1.mp4#view@*",
            "videos:/cats/1.mp4#view@cat lady",
            "videos:/cats/2.mp4#view@cat lady",
            "videos:/cats/2.mp4#view@john",
            "videos:/cats#view@cat lady",
            "videos:/cats#owner@cat lady",
            "videos:/cats/1.mp4#owner@cat lady",
        ]
        rts = [RelationTuple.from_string(q) for q in queries]
        got = e.check_batch(rts)
        want = [e.reference.check_relation_tuple(t, 0) for t in rts]
        for q, g, w in zip(queries, got, want):
            assert g.membership == w.membership, q
        # all these are monotone: the device must have answered them
        assert e.stats["host_checks"] == 0

    @pytest.mark.parametrize("query,expected", REWRITE_CASES)
    def test_rewrite_fixtures(self, rewrite_tpu_engine, query, expected):
        res = rewrite_tpu_engine.check_batch(
            [RelationTuple.from_string(query)], 100
        )[0]
        assert res.error is None
        assert (res.membership == Membership.IS_MEMBER) == expected, query

    def test_and_not_islands_run_on_device(self):
        """AND/NOT rewrites execute as device islands (VERDICT round-1
        item 4): every REWRITE_CASE — including acl's AND + NOT(deny) and
        resource's AND(owner, TTU) — answers from the kernel, matching
        the exact host engine. The ONLY host replay allowed is the
        unknown-object query (object absent from graph + vocab — the
        documented exact-host path, unrelated to islands)."""
        unknown_vocab = {"doc:another_doc#viewer@user"}
        e = make_tpu_engine(REWRITE_NAMESPACES, REWRITE_TUPLES, max_depth=100)
        rts = [RelationTuple.from_string(q) for q, _ in REWRITE_CASES]
        got = e.check_batch(rts, 100)
        for (q, expected), g in zip(REWRITE_CASES, got):
            assert g.error is None, q
            assert (g.membership == Membership.IS_MEMBER) == expected, q
        assert e.stats["host_checks"] == len(unknown_vocab)
        assert e.stats["device_checks"] == len(rts) - len(unknown_vocab)

    def test_deep_chain_topology(self):
        # the reference benchmark's "deep" namespace (bench_test.go:56-86)
        max_depth = 32
        namespaces = [
            Namespace(
                name="deep",
                relations=[
                    Relation(name="owner"),
                    Relation(name="parent"),
                    Relation(
                        name="editor",
                        subject_set_rewrite=SubjectSetRewrite(
                            children=[ComputedSubjectSet(relation="owner")]
                        ),
                    ),
                    Relation(
                        name="viewer",
                        subject_set_rewrite=SubjectSetRewrite(
                            children=[
                                ComputedSubjectSet(relation="editor"),
                                TupleToSubjectSet(
                                    relation="parent",
                                    computed_subject_set_relation="viewer",
                                ),
                            ]
                        ),
                    ),
                ],
            )
        ]
        tuples = ["deep:deep_file#parent@(deep:folder_1#...)"]
        for i in range(1, max_depth):
            tuples.append(f"deep:folder_{i}#parent@(deep:folder_{i + 1}#...)")
        for d in (2, 4, 8, 16, 32):
            tuples.append(f"deep:folder_{d}#owner@user_{d}")
        e = make_tpu_engine(namespaces, tuples, max_depth=100 * max_depth)
        for d in (2, 4, 8, 16, 32):
            q = RelationTuple.from_string(f"deep:deep_file#viewer@user_{d}")
            res = e.check_batch([q], 2 * d)[0]
            ref = e.reference.check_relation_tuple(q, 2 * d)
            assert res.membership == ref.membership, f"depth {d}"
            assert res.membership == Membership.IS_MEMBER
        # not enough depth: reference and kernel agree on the miss
        q = RelationTuple.from_string("deep:deep_file#viewer@user_32")
        res = e.check_batch([q], 3)[0]
        assert res.membership == Membership.NOT_MEMBER
        assert e.stats["host_checks"] == 0

    def test_wide_union_topology(self):
        # the reference benchmark's wide namespace (bench_test.go:19-46)
        width = 40
        relations = [Relation(name="editor")]
        children = []
        for i in range(width):
            relations.append(Relation(name=f"relation-{i}"))
            children.append(ComputedSubjectSet(relation=f"relation-{i}"))
        children.append(ComputedSubjectSet(relation="editor"))
        relations.append(
            Relation(name="viewer", subject_set_rewrite=SubjectSetRewrite(children=children))
        )
        ns = Namespace(name="wide", relations=relations)
        e = make_tpu_engine([ns], ["wide:file#editor@user"], max_depth=80)
        q = RelationTuple.from_string("wide:file#viewer@user")
        res = e.check_batch([q], 80)[0]
        assert res.membership == Membership.IS_MEMBER
        # width exceeds the instruction cap K=8: korrectly host-flagged
        assert e.stats["host_checks"] == 1

    def test_circular_graph(self):
        e = make_tpu_engine(
            [Namespace(name="n")],
            [
                "n:a#r@(n:b#r)",
                "n:b#r@(n:c#r)",
                "n:c#r@(n:a#r)",
                "n:c#r@deep-user",
            ],
            max_depth=10,
        )
        for q, want in [
            ("n:a#r@deep-user", True),
            ("n:b#r@deep-user", True),
            ("n:a#r@nobody", False),
        ]:
            res = e.check_batch([RelationTuple.from_string(q)], 10)[0]
            assert (res.membership == Membership.IS_MEMBER) == want, q

    def test_subject_set_query_subject(self):
        # query whose subject is itself a subject set: direct probe must
        # match subject-set edges exactly
        e = make_tpu_engine(
            [Namespace(name="n")],
            ["n:o#r@(n:o2#r2)"],
        )
        q = RelationTuple.make("n", "o", "r", SubjectSet("n", "o2", "r2"))
        assert e.check_batch([q])[0].membership == Membership.IS_MEMBER
        q2 = RelationTuple.make("n", "o", "r", SubjectSet("n", "o2", "other"))
        assert e.check_batch([q2])[0].membership == Membership.NOT_MEMBER

    def test_randomized_differential(self):
        rng = random.Random(42)
        n_objects = 30
        n_users = 10
        relations = ["r0", "r1", "r2"]
        namespaces = [
            Namespace(
                name="rnd",
                relations=[
                    Relation(name="r0"),
                    Relation(name="r1"),
                    Relation(
                        name="r2",
                        subject_set_rewrite=SubjectSetRewrite(
                            children=[
                                ComputedSubjectSet(relation="r0"),
                                TupleToSubjectSet(
                                    relation="r1",
                                    computed_subject_set_relation="r2",
                                ),
                            ]
                        ),
                    ),
                ],
            )
        ]
        for trial in range(5):
            tuples = set()
            for _ in range(120):
                obj = f"o{rng.randrange(n_objects)}"
                rel = rng.choice(relations)
                if rng.random() < 0.45:
                    sub = f"(rnd:o{rng.randrange(n_objects)}#{rng.choice(relations)})"
                else:
                    sub = f"u{rng.randrange(n_users)}"
                tuples.add(f"rnd:{obj}#{rel}@{sub}")
            # generous depth so visited-pruning order effects vanish
            e = make_tpu_engine(namespaces, sorted(tuples), max_depth=12)
            queries = []
            for _ in range(64):
                queries.append(
                    RelationTuple.from_string(
                        f"rnd:o{rng.randrange(n_objects)}#"
                        f"{rng.choice(relations)}@u{rng.randrange(n_users)}"
                    )
                )
            got = e.check_batch(queries, 12)
            for q, g in zip(queries, got):
                ref = e.reference.check_relation_tuple(q, 12)
                assert g.membership == ref.membership, f"trial {trial}: {q}"

    def test_read_your_writes(self):
        cfg = Config({"limit": {"max_read_depth": 5}})
        cfg.set_namespaces([Namespace(name="n")])
        m = MemoryManager()
        e = TPUCheckEngine(m, cfg)
        q = RelationTuple.from_string("n:o#r@u")
        assert e.check_batch([q])[0].membership == Membership.NOT_MEMBER
        m.write_relation_tuples([q])
        assert e.check_batch([q])[0].membership == Membership.IS_MEMBER
        m.delete_relation_tuples([q])
        assert e.check_batch([q])[0].membership == Membership.NOT_MEMBER
        # the delta overlay serves read-your-writes without rebuilds
        assert e.stats["snapshot_builds"] == 1

    def test_large_batch_spans_buckets(self):
        tuples = [f"n:o{i}#r@u{i}" for i in range(50)]
        e = make_tpu_engine([Namespace(name="n")], tuples)
        queries = [RelationTuple.from_string(f"n:o{i}#r@u{i}") for i in range(50)]
        queries += [RelationTuple.from_string(f"n:o{i}#r@u{i + 1}") for i in range(50)]
        got = e.check_batch(queries)
        assert all(r.membership == Membership.IS_MEMBER for r in got[:50])
        assert all(r.membership == Membership.NOT_MEMBER for r in got[50:])


class TestReviewRegressions:
    def test_data_only_relation_in_configured_namespace_errors(self):
        # reference: namespace has a relation config, queried relation not
        # declared -> error (engine.go:219-228). A directly-matching tuple
        # instead wins the OR race (one legal schedule) -> IsMember.
        e = make_tpu_engine(
            [Namespace(name="n", relations=[Relation(name="known")])],
            ["n:o#rogue@u"],
        )
        # direct hit: both paths say IsMember, no error
        hit = e.check_batch([RelationTuple.from_string("n:o#rogue@u")])[0]
        assert hit.membership == Membership.IS_MEMBER and hit.error is None
        # miss: the undeclared relation surfaces as an error on both paths
        res = e.check_batch([RelationTuple.from_string("n:o#rogue@v")])[0]
        ref = e.reference.check_relation_tuple(
            RelationTuple.from_string("n:o#rogue@v")
        )
        assert res.error is not None and ref.error is not None
        assert type(res.error) is type(ref.error)

    def test_namespace_config_change_invalidates_snapshot(self):
        cfg = Config({"limit": {"max_read_depth": 5}})
        cfg.set_namespaces([
            Namespace(name="n", relations=[Relation(name="owner"), Relation(name="editor")])
        ])
        m = MemoryManager()
        m.write_relation_tuples([RelationTuple.from_string("n:o#owner@u")])
        e = TPUCheckEngine(m, cfg)
        q = RelationTuple.from_string("n:o#editor@u")
        assert e.check_batch([q])[0].membership == Membership.NOT_MEMBER
        # add a rewrite (editor includes owner) WITHOUT any tuple write
        cfg.set_namespaces([
            Namespace(
                name="n",
                relations=[
                    Relation(name="owner"),
                    Relation(
                        name="editor",
                        subject_set_rewrite=SubjectSetRewrite(
                            children=[ComputedSubjectSet(relation="owner")]
                        ),
                    ),
                ],
            )
        ])
        assert e.check_batch([q])[0].membership == Membership.IS_MEMBER

    def test_step_exhaustion_falls_back_to_host(self):
        # interleaved computed+TTU chain: ~2 BFS steps per level; depth
        # clamp 100 over 60 levels exceeds the kernel step budget, which
        # must flag needs_host instead of silently denying
        ns = Namespace(
            name="d",
            relations=[
                Relation(name="owner"),
                Relation(name="parent"),
                Relation(
                    name="w",
                    subject_set_rewrite=SubjectSetRewrite(
                        children=[
                            ComputedSubjectSet(relation="owner"),
                            TupleToSubjectSet(
                                relation="parent",
                                computed_subject_set_relation="v",
                            ),
                        ]
                    ),
                ),
                Relation(
                    name="v",
                    subject_set_rewrite=SubjectSetRewrite(
                        children=[ComputedSubjectSet(relation="w")]
                    ),
                ),
            ],
        )
        levels = 60
        tuples = ["d:f0#parent@(d:f1#...)"]
        for i in range(1, levels):
            tuples.append(f"d:f{i}#parent@(d:f{i + 1}#...)")
        tuples.append(f"d:f{levels}#owner@user")
        e = make_tpu_engine([ns], tuples, max_depth=100)
        q = RelationTuple.from_string("d:f0#v@user")
        res = e.check_batch([q], 100)[0]
        ref = e.reference.check_relation_tuple(q, 100)
        assert res.membership == ref.membership == Membership.IS_MEMBER
        assert e.stats["host_checks"] == 1  # exhaustion was flagged

    def test_small_frontier_cap_splits_batches(self):
        e = TPUCheckEngine(
            MemoryManager(),
            _cfg_with([Namespace(name="n")]),
            frontier_cap=16,
        )
        queries = [RelationTuple.from_string(f"n:o{i}#r@u") for i in range(40)]
        res = e.check_batch(queries)
        assert len(res) == 40
        assert all(r.membership == Membership.NOT_MEMBER for r in res)


def _cfg_with(namespaces):
    cfg = Config({"limit": {"max_read_depth": 5}})
    cfg.set_namespaces(namespaces)
    return cfg


class TestIslands:
    """Device-island semantics: AND/NOT full-evaluation islands
    (engine/snapshot.py _compile_rewrite + engine/islands.py combine)
    differentially against the exact host engine."""

    def _engine(self, namespaces, tuples, max_depth=8):
        return make_tpu_engine(namespaces, tuples, max_depth=max_depth)

    def test_nested_not_not(self):
        from keto_tpu.namespace.ast import InvertResult

        ns = [Namespace(name="n", relations=[
            Relation(name="a"),
            Relation(name="dbl", subject_set_rewrite=SubjectSetRewrite(children=[
                InvertResult(child=InvertResult(
                    child=ComputedSubjectSet(relation="a"))),
            ])),
        ])]
        e = self._engine(ns, ["n:x#a@u1"])
        cases = ["n:x#dbl@u1", "n:x#dbl@u2"]
        got = e.check_batch([RelationTuple.from_string(c) for c in cases])
        for c, g in zip(cases, got):
            ref = e.reference.check_relation_tuple(RelationTuple.from_string(c), 0)
            assert g.membership == ref.membership, c
        assert e.stats["host_checks"] == 0

    def test_nested_islands_along_ttu_chain(self):
        """view = owner | ttu(parent, view); owner = granted & not(revoked):
        every folder hop spawns a nested island under the previous one."""
        from keto_tpu.namespace.ast import InvertResult, Operator

        ns = [Namespace(name="f", relations=[
            Relation(name="granted"),
            Relation(name="revoked"),
            Relation(name="parent"),
            Relation(name="owner", subject_set_rewrite=SubjectSetRewrite(
                operation=Operator.AND,
                children=[
                    ComputedSubjectSet(relation="granted"),
                    InvertResult(child=ComputedSubjectSet(relation="revoked")),
                ])),
            Relation(name="view", subject_set_rewrite=SubjectSetRewrite(children=[
                ComputedSubjectSet(relation="owner"),
                TupleToSubjectSet(relation="parent",
                                  computed_subject_set_relation="view"),
            ])),
        ])]
        tuples = [
            "f:root#granted@alice",
            "f:root#granted@bob",
            "f:root#revoked@bob",
            "f:mid#parent@(f:root#...)",
            "f:leaf#parent@(f:mid#...)",
            "f:leaf#granted@carol",
        ]
        e = self._engine(ns, tuples, max_depth=10)
        cases = [
            "f:leaf#view@alice",   # root grant propagates down
            "f:leaf#view@bob",     # revoked at root: denied everywhere
            "f:leaf#view@carol",   # direct grant on the leaf
            "f:mid#view@carol",    # carol has nothing above the leaf
            "f:root#owner@bob",    # AND + NOT island at the root itself
        ]
        got = e.check_batch([RelationTuple.from_string(c) for c in cases], 10)
        for c, g in zip(cases, got):
            ref = e.reference.check_relation_tuple(RelationTuple.from_string(c), 10)
            assert g.membership == ref.membership, c
        assert e.stats["host_checks"] == 0

    def test_depth_exhaustion_under_not_matches_reference(self):
        """not(deep-chain) where the chain exceeds max_depth: the
        reference collapses the exhausted branch to NotMember and the NOT
        flips it to ALLOWED — the device must reproduce exactly that
        (deliberate parity, however security-questionable)."""
        from keto_tpu.namespace.ast import InvertResult, Operator

        ns = [Namespace(name="d", relations=[
            Relation(name="deny"),
            Relation(name="link"),
            Relation(name="denied_deep", subject_set_rewrite=SubjectSetRewrite(
                children=[
                    ComputedSubjectSet(relation="deny"),
                    TupleToSubjectSet(relation="link",
                                      computed_subject_set_relation="denied_deep"),
                ])),
            Relation(name="ok", subject_set_rewrite=SubjectSetRewrite(children=[
                InvertResult(child=ComputedSubjectSet(relation="denied_deep")),
            ])),
        ])]
        chain = 6
        tuples = [f"d:n{i}#link@(d:n{i+1}#...)" for i in range(chain)]
        tuples.append(f"d:n{chain}#deny@mallory")
        for depth in (3, chain + 3):  # exhausted vs fully explored
            e = self._engine(ns, tuples, max_depth=depth)
            for sub in ("mallory", "alice"):
                q = RelationTuple.from_string(f"d:n0#ok@{sub}")
                g = e.check_batch([q], depth)[0]
                ref = e.reference.check_relation_tuple(q, depth)
                assert g.membership == ref.membership, (depth, sub)
            assert e.stats["host_checks"] == 0

    def test_randomized_differential_with_islands(self):
        """Random graphs whose relation rewrites include AND and NOT
        nodes (acyclic in relation space so the reference terminates)."""
        from keto_tpu.namespace.ast import InvertResult, Operator

        rng = random.Random(1234)
        n_objects, n_users = 24, 8
        rel_names = [f"r{i}" for i in range(6)]

        def random_rewrite(i):
            # children may only reference strictly higher relation ids
            higher = rel_names[i + 1 :]
            if not higher or rng.random() < 0.3:
                return None

            def leaf():
                r = rng.choice(higher)
                if rng.random() < 0.5:
                    return ComputedSubjectSet(relation=r)
                return TupleToSubjectSet(
                    relation=rng.choice(rel_names),
                    computed_subject_set_relation=r,
                )

            def node(budget):
                roll = rng.random()
                if budget <= 0 or roll < 0.45:
                    return leaf()
                if roll < 0.6:
                    return InvertResult(child=node(budget - 1))
                op = Operator.AND if rng.random() < 0.5 else Operator.OR
                return SubjectSetRewrite(
                    operation=op,
                    children=[node(budget - 1) for _ in range(rng.randrange(2, 4))],
                )

            rw = node(2)
            if not isinstance(rw, SubjectSetRewrite):
                rw = SubjectSetRewrite(children=[rw])
            return rw

        for trial in range(4):
            relations = [
                Relation(name=r, subject_set_rewrite=random_rewrite(i))
                for i, r in enumerate(rel_names)
            ]
            namespaces = [Namespace(name="rnd", relations=relations)]
            tuples = set()
            for _ in range(150):
                obj = f"o{rng.randrange(n_objects)}"
                rel = rng.choice(rel_names)
                if rng.random() < 0.4:
                    sub = f"(rnd:o{rng.randrange(n_objects)}#{rng.choice(rel_names)})"
                else:
                    sub = f"u{rng.randrange(n_users)}"
                tuples.add(f"rnd:{obj}#{rel}@{sub}")
            e = make_tpu_engine(namespaces, sorted(tuples), max_depth=10)
            queries = [
                RelationTuple.from_string(
                    f"rnd:o{rng.randrange(n_objects)}#"
                    f"{rng.choice(rel_names)}@u{rng.randrange(n_users)}"
                )
                for _ in range(64)
            ]
            got = e.check_batch(queries, 10)
            # cyclic random graphs: the reference's shared visited-set
            # makes pruned traversal order-dependent (the Go original is
            # racy there — goroutine scheduling decides); the kernel
            # implements the deterministic pruning-free semantics, so
            # that's the oracle (same choice as test_sharded)
            oracle = ReferenceEngine(e.manager, e.config, visited_pruning=False)
            for q, g in zip(queries, got):
                ref = oracle.check_relation_tuple(q, 10)
                assert g.membership == ref.membership, f"trial {trial}: {q}"


class TestHostFallbackCauses:
    """VERDICT r2 item 7: host fallback must be observable by cause —
    "host because AND/NOT overflow" distinguishable from "host because
    error" — via stats["host_cause"] and the labeled Prometheus counter."""

    def test_rewrite_cap_pinned(self):
        # a union rewrite with > rewrite_instr_cap children compiles to
        # FLAG_HOST_ONLY (snapshot.py _compile); its queries host-replay
        # with cause "rewrite_cap" and still return exact verdicts
        K = 8  # TPUCheckEngine default rewrite_instr_cap
        rels = [Relation(name=f"r{i}") for i in range(K + 1)]
        wide = Relation(
            name="wide",
            subject_set_rewrite=SubjectSetRewrite(
                children=[
                    ComputedSubjectSet(relation=f"r{i}") for i in range(K + 1)
                ]
            ),
        )
        ns = Namespace(name="w", relations=rels + [wide])
        e = make_tpu_engine([ns], [f"w:o#r{K}@alice"])  # hit via LAST branch
        got = e.check_batch(
            [
                RelationTuple.from_string("w:o#wide@alice"),
                RelationTuple.from_string("w:o#wide@bob"),
            ]
        )
        assert got[0].membership == Membership.IS_MEMBER
        assert got[1].membership == Membership.NOT_MEMBER
        assert e.stats["host_checks"] == 2
        assert e.stats["host_cause"] == {"rewrite_cap": 2}

    def test_relation_not_found_cause(self):
        e = make_tpu_engine(
            [Namespace(name="n", relations=[Relation(name="known")])],
            ["n:o#rogue@u"],
        )
        res = e.check_batch([RelationTuple.from_string("n:o#rogue@v")])[0]
        assert res.error is not None
        assert e.stats["host_cause"] == {"relation_not_found": 1}

    def test_unindexed_cause(self):
        e = make_tpu_engine([Namespace(name="n")], ["n:o#r@u"])
        e.check_batch([RelationTuple.from_string("ghost:o#r@u")])
        assert e.stats["host_cause"] == {"unindexed": 1}

    def test_island_overflow_cause(self):
        # one query fanning out (via TTU) to more AND/NOT islands than
        # island_cap = 2*B can hold: exact verdict via host replay,
        # cause "island_overflow" — the capacity cliff the cause split
        # exists to expose
        from keto_tpu.namespace.ast import InvertResult, Operator

        n_docs = 40  # > island_cap (2 * bucket16 = 32)
        ns = Namespace(
            name="acl",
            relations=[
                Relation(name="allow"),
                Relation(name="deny"),
                Relation(name="parent"),
                Relation(
                    name="access",
                    subject_set_rewrite=SubjectSetRewrite(
                        operation=Operator.AND,
                        children=[
                            ComputedSubjectSet(relation="allow"),
                            InvertResult(
                                child=ComputedSubjectSet(relation="deny")
                            ),
                        ],
                    ),
                ),
                Relation(
                    name="super",
                    subject_set_rewrite=SubjectSetRewrite(
                        children=[
                            TupleToSubjectSet(
                                relation="parent",
                                computed_subject_set_relation="access",
                            )
                        ]
                    ),
                ),
            ],
        )
        tuples = [f"acl:root#parent@(acl:doc{i}#...)" for i in range(n_docs)]
        tuples.append(f"acl:doc{n_docs - 1}#allow@alice")
        e = make_tpu_engine([ns], tuples)
        res = e.check_batch([RelationTuple.from_string("acl:root#super@alice")])
        assert res[0].membership == Membership.IS_MEMBER
        assert e.stats["host_cause"] == {"island_overflow": 1}

    def test_prometheus_counter_labels(self):
        from keto_tpu.observability import Metrics

        K = 8
        rels = [Relation(name=f"r{i}") for i in range(K + 1)]
        wide = Relation(
            name="wide",
            subject_set_rewrite=SubjectSetRewrite(
                children=[
                    ComputedSubjectSet(relation=f"r{i}") for i in range(K + 1)
                ]
            ),
        )
        cfg = Config({"limit": {"max_read_depth": 5}})
        cfg.set_namespaces([Namespace(name="w", relations=rels + [wide])])
        m = MemoryManager()
        m.write_relation_tuples([RelationTuple.from_string("w:o#r0@u")])
        metrics = Metrics()
        e = TPUCheckEngine(m, cfg, metrics=metrics)
        e.check_batch([RelationTuple.from_string("w:o#wide@u")] * 2)
        text = metrics.export().decode()
        assert 'keto_tpu_host_fallback_total{cause="rewrite_cap"} 2.0' in text


class TestCountedLoopBranch:
    """bounded_loop picks fori+cond on TPU-class backends and while_loop
    on CPU (engine/kernel.counted_loop_backend). CPU test runs would
    otherwise never execute the counted branch — force it and pin the
    differential so the on-chip construct stays covered off-chip.

    Forcing requires clearing jit caches: earlier tests pre-warm traces
    for the same (shapes, statics), and a cached executable would bypass
    the patched selector entirely — each test asserts the selector
    actually RAN during tracing (review r5 finding: the unasserted
    version was vacuous)."""

    @pytest.fixture(autouse=True)
    def _cache_hygiene(self):
        """Forced-branch executables must not leak into the global jit
        cache (a later same-shape test would silently run the wrong
        construct), and stale pre-force caches must not swallow the
        forced trace — clear on both edges."""
        import jax

        jax.clear_caches()
        yield
        jax.clear_caches()

    def _force_counted(self, monkeypatch):
        import jax

        from keto_tpu.engine import kernel as kmod

        calls = {"n": 0}

        def forced():
            calls["n"] += 1
            return True

        # both TPU-class choices flip together: the point is covering
        # the on-chip configuration (counted loop + scan seg map) on CPU
        monkeypatch.setattr(kmod, "counted_loop_backend", forced)
        monkeypatch.setattr(kmod, "scan_seg_map_backend", forced)
        jax.clear_caches()
        return calls

    def test_counted_branch_matches_reference(self, monkeypatch):
        calls = self._force_counted(monkeypatch)
        e = make_tpu_engine(REWRITE_NAMESPACES, REWRITE_TUPLES, max_depth=100)
        for query, expected in REWRITE_CASES:
            res = e.check_batch([RelationTuple.from_string(query)], 100)[0]
            assert res.error is None
            want = expected == Membership.IS_MEMBER
            assert res.allowed == want, query
        assert calls["n"] > 0, "counted branch never traced (cache hit?)"

    def test_counted_branch_early_exit_equivalence(self, monkeypatch):
        """A batch that resolves in ~2 steps must produce identical
        verdicts through both loop constructs (the cond pass-through
        must not perturb state)."""
        ns = [Namespace(name="n", relations=[Relation(name="r")])]
        tuples = [f"n:o{i}#r@u{i}" for i in range(64)]
        queries = [
            RelationTuple.from_string(f"n:o{i}#r@u{i % 3}") for i in range(64)
        ]
        e1 = make_tpu_engine(ns, tuples)
        base = [r.allowed for r in e1.check_batch(queries)]
        calls = self._force_counted(monkeypatch)
        e2 = make_tpu_engine(ns, tuples)
        forced = [r.allowed for r in e2.check_batch(queries)]
        assert forced == base
        assert calls["n"] > 0, "counted branch never traced (cache hit?)"

    def test_counted_branch_expand_kernel(self, monkeypatch):
        """The expand kernel shares bounded_loop; its counted branch
        must assemble identical trees."""
        ns = [Namespace(name="n", relations=[
            Relation(name="r"), Relation(name="g"),
        ])]
        tuples = (
            [f"n:o#r@(n:m{i}#g)" for i in range(4)]
            + [f"n:m{i}#g@u{j}" for i in range(4) for j in range(3)]
        )
        e1 = make_tpu_engine(ns, tuples)
        sub = SubjectSet("n", "o", "r")
        base = e1.expand_batch([sub], 4)[0]
        calls = self._force_counted(monkeypatch)
        e2 = make_tpu_engine(ns, tuples)
        forced = e2.expand_batch([sub], 4)[0]
        assert str(forced) == str(base)
        assert calls["n"] > 0, "counted branch never traced (cache hit?)"

