"""SQL dialect layer: golden SQL-shape tests.

The reference proves its four dialects by running migrations against
live Postgres/MySQL/Cockroach/SQLite containers
(internal/x/dbx/dsn_testutils.go:106-151). Only sqlite has a driver in
this environment, so the other three renderings are pinned at the SQL
level: every divergence a live engine would reject (TEXT index keys on
MySQL, partial-index WHERE clauses, upsert spellings, placeholder
styles) is asserted here, and the sqlite rendering is additionally
executed end-to-end by the whole test_store.py suite.
"""

import re

import pytest

from keto_tpu.storage.dialect import (
    CockroachDialect,
    MySQLDialect,
    PostgresDialect,
    SQLiteDialect,
    StoreDriverMissing,
    dialect_for_dsn,
)
from keto_tpu.storage.sqlite import (
    MIGRATIONS,
    SQLPersister,
    render_migrations,
)


def _sql_steps(migs):
    for _version, ups, downs in migs:
        for s in [*ups, *downs]:
            if not s.startswith("__"):
                yield s


class TestRendering:
    def test_sqlite_rendering_is_module_migrations(self):
        assert render_migrations(SQLiteDialect()) == MIGRATIONS

    def test_no_unrendered_placeholders_any_dialect(self):
        for d in (SQLiteDialect(), PostgresDialect(), CockroachDialect(),
                  MySQLDialect()):
            for s in _sql_steps(render_migrations(d)):
                assert "{" not in s and "}" not in s, (d.name, s)

    def test_sqlite_uses_sqlite_idioms(self):
        sql = "\n".join(_sql_steps(MIGRATIONS))
        assert "AUTOINCREMENT" in sql
        assert "strftime" in sql
        assert "WHERE subject_id IS NOT NULL" in sql  # partial index kept

    def test_postgres_types_and_idioms(self):
        sql = "\n".join(_sql_steps(render_migrations(PostgresDialect())))
        assert "UUID" in sql and "BIGSERIAL PRIMARY KEY" in sql
        assert "extract(epoch from now())" in sql
        assert "strftime" not in sql and "AUTOINCREMENT" not in sql
        # partial reverse indexes survive (the reference's postgres DDL
        # keeps them: …uuid-table.postgres.up.sql)
        assert "WHERE subject_id IS NOT NULL" in sql

    def test_cockroach_is_postgres_with_serial(self):
        sql = "\n".join(_sql_steps(render_migrations(CockroachDialect())))
        assert "SERIAL PRIMARY KEY" in sql and "BIGSERIAL" not in sql
        assert "UUID" in sql

    def test_mysql_drops_partial_indexes(self):
        # "mysql has no partial indexes so we can only use the full one"
        # — the reference's own mysql DDL comment
        sql = "\n".join(_sql_steps(render_migrations(MySQLDialect())))
        assert "WHERE subject_id IS NOT NULL" not in sql
        assert "WHERE subject_set_namespace IS NOT NULL" not in sql
        assert "CHAR(36)" in sql and "AUTO_INCREMENT" in sql

    def test_mysql_strips_if_not_exists_on_create_index(self):
        # MySQL rejects CREATE INDEX IF NOT EXISTS (error 1064); tables
        # keep the clause (supported there)
        sql_steps = list(_sql_steps(render_migrations(MySQLDialect())))
        assert any("CREATE INDEX" in s for s in sql_steps)
        for s in sql_steps:
            if "CREATE INDEX" in s:
                assert "IF NOT EXISTS" not in s, s
            if "CREATE TABLE" in s:
                assert "IF NOT EXISTS" in s, s

    def test_change_log_prune_avoids_mysql_1093(self):
        # MySQL rejects DELETE with a subquery on the target table; the
        # prune statement (now in _trim, which _log_changes drives) must
        # read through a derived table on every dialect (it is canonical
        # SQL, prepped not rendered)
        import inspect

        from keto_tpu.storage import sqlite as sqlite_mod

        src = inspect.getsource(sqlite_mod.SQLPersister._trim)
        assert "AS boundary" in src

    def _change_log_steps(self, dialect):
        for version, ups, _downs in render_migrations(dialect):
            if version == "20220513200303_create_change_log":
                return ups
        raise AssertionError("change-log migration missing")

    def test_change_log_ddl_golden_shapes(self):
        # the watch subsystem's durable feed: one template, four
        # dialect renderings (the reference hand-writes each migration
        # per engine; keto_change_log has no reference analog so these
        # goldens pin OUR contract: autoincrementing seq PK, typed nid/
        # op columns, the (nid, version) tail index)
        sqlite_sql = "\n".join(self._change_log_steps(SQLiteDialect()))
        assert "seq INTEGER PRIMARY KEY AUTOINCREMENT" in sqlite_sql
        assert "nid TEXT NOT NULL" in sqlite_sql
        assert "op TEXT NOT NULL" in sqlite_sql
        assert (
            "keto_change_log_nid_version_idx" in sqlite_sql
            and "(nid, version)" in sqlite_sql
        )

        pg_sql = "\n".join(self._change_log_steps(PostgresDialect()))
        assert "seq BIGSERIAL PRIMARY KEY" in pg_sql
        assert "nid VARCHAR(64) NOT NULL" in pg_sql
        assert "op VARCHAR(16) NOT NULL" in pg_sql

        crdb_sql = "\n".join(self._change_log_steps(CockroachDialect()))
        assert "seq SERIAL PRIMARY KEY" in crdb_sql
        assert "BIGSERIAL" not in crdb_sql

        mysql_sql = "\n".join(self._change_log_steps(MySQLDialect()))
        assert "seq BIGINT NOT NULL AUTO_INCREMENT PRIMARY KEY" in mysql_sql
        # MySQL can't CREATE INDEX IF NOT EXISTS; the index step must
        # have the clause stripped like every other mysql index
        for step in self._change_log_steps(MySQLDialect()):
            if "CREATE INDEX" in step:
                assert "IF NOT EXISTS" not in step

    def test_postgres_transient_classification(self):
        d = PostgresDialect()
        # permanent: fail startup immediately (no 60s auth hammering)
        for msg in (
            'connection to server at "h" (1.2.3.4), port 5432 failed:'
            " FATAL:  password authentication failed for user \"u\"",
            'connection to server at "h" failed: FATAL:  database'
            ' "nope" does not exist',
        ):
            assert not d.is_transient(RuntimeError(msg)), msg
        # transient: retry inside the backoff window
        for msg in (
            'connection to server at "h", port 5432 failed: Connection'
            " refused",
            "could not connect to server: Connection refused",
            "FATAL:  the database system is starting up",
            "FATAL:  sorry, too many clients already",
        ):
            assert d.is_transient(RuntimeError(msg)), msg

    def test_mysql_never_indexes_text_columns(self):
        # MySQL rejects TEXT keys without a prefix length; every indexed
        # column must render as a bounded type. TEXT is allowed only for
        # never-indexed payloads (mapping strings, change-log tuples).
        migs = render_migrations(MySQLDialect())
        for s in _sql_steps(migs):
            m = re.search(r"CREATE TABLE IF NOT EXISTS (\w+)\s*\((.*)\)\s*$",
                          s, re.S)
            if not m:
                continue
            body = m.group(2)
            text_cols = re.findall(r"(\w+)\s+TEXT\b", body)
            assert set(text_cols) <= {"string_representation", "tuple"}, s
        # and the index DDL itself names no TEXT column
        for s in _sql_steps(migs):
            if "CREATE INDEX" in s:
                assert "string_representation" not in s
                assert re.search(r"\btuple\b", s) is None

    def test_versions_and_step_counts_match_across_dialects(self):
        base = [(v, len(u), len(d)) for v, u, d in MIGRATIONS]
        for d in (PostgresDialect(), CockroachDialect(), MySQLDialect()):
            assert [(v, len(u), len(dn))
                    for v, u, dn in render_migrations(d)] == base


class TestStatements:
    def test_prep_placeholders(self):
        q = "SELECT 1 FROM t WHERE a = ? AND b = ?"
        assert SQLiteDialect().prep(q) == q
        assert PostgresDialect().prep(q) == (
            "SELECT 1 FROM t WHERE a = %s AND b = %s"
        )
        assert MySQLDialect().prep(q).count("%s") == 2

    def test_insert_ignore_spellings(self):
        cols = ("a", "b")
        assert SQLiteDialect().insert_ignore("t", cols).startswith(
            "INSERT OR IGNORE INTO t"
        )
        assert MySQLDialect().insert_ignore("t", cols).startswith(
            "INSERT IGNORE INTO t"
        )
        pg = PostgresDialect().insert_ignore("t", cols)
        assert pg.startswith("INSERT INTO t") and "ON CONFLICT DO NOTHING" in pg

    def test_version_upsert_spellings(self):
        assert "ON CONFLICT(nid) DO UPDATE" in SQLiteDialect().version_upsert()
        # postgres must table-qualify the incremented column
        assert ("keto_store_version.version + 1"
                in PostgresDialect().version_upsert())
        assert "ON DUPLICATE KEY UPDATE" in MySQLDialect().version_upsert()

    def test_delete_aliased_spellings(self):
        w = "t.nid = ?"
        assert SQLiteDialect().delete_aliased("x", "t", w) == (
            "DELETE FROM x AS t WHERE t.nid = ?"
        )
        # mysql's only aliased form is the multi-table DELETE
        assert MySQLDialect().delete_aliased("x", "t", w) == (
            "DELETE t FROM x AS t WHERE t.nid = ?"
        )

    def test_table_exists_probe_targets(self):
        assert "sqlite_master" in SQLiteDialect().table_exists_sql()
        assert "information_schema" in PostgresDialect().table_exists_sql()
        assert "information_schema" in MySQLDialect().table_exists_sql()


class TestRouting:
    def test_memory_and_sqlite_urls_route_to_sqlite(self):
        for dsn, want in [
            ("memory", ":memory:"),
            (":memory:", ":memory:"),
            ("sqlite:///tmp/db.sqlite", "/tmp/db.sqlite"),
        ]:
            d, out = dialect_for_dsn(dsn)
            assert isinstance(d, SQLiteDialect) and out == want

    def test_bare_strings_rejected_as_typos(self):
        # 'Memory' / a bare path must not silently become a fresh sqlite
        # file; file databases are spelled sqlite://<path> (or use
        # SQLitePersister, which binds the dialect explicitly)
        for dsn in ("Memory", "colummnar", "/tmp/db.sqlite"):
            with pytest.raises(ValueError, match="unsupported DSN"):
                dialect_for_dsn(dsn)

    def test_network_schemes_route_and_keep_url(self):
        for scheme, cls in [
            ("postgres", PostgresDialect),
            ("postgresql", PostgresDialect),
            ("cockroach", CockroachDialect),
            ("cockroachdb", CockroachDialect),
            ("mysql", MySQLDialect),
        ]:
            dsn = f"{scheme}://u:p@h:1/db"
            d, out = dialect_for_dsn(dsn)
            assert type(d) is cls and out == dsn

    def test_unknown_scheme_raises(self):
        with pytest.raises(ValueError, match="unsupported DSN"):
            dialect_for_dsn("oracle://u@h/db")

    def test_missing_driver_is_loud_and_named(self):
        # the drivers are deliberately absent from this image; the DSN
        # must fail at construction with the driver named, not at first
        # query with an AttributeError
        with pytest.raises(StoreDriverMissing, match="psycopg2"):
            SQLPersister("postgres://u:p@localhost/keto")
        with pytest.raises(StoreDriverMissing, match="pymysql"):
            SQLPersister("mysql://u:p@localhost/keto")

    def test_registry_routes_network_dsn_to_dialect_layer(self):
        from keto_tpu.config import Config
        from keto_tpu.registry import Registry

        cfg = Config(
            {"dsn": "postgres://u:p@localhost/keto", "namespaces": []}
        )
        with pytest.raises(StoreDriverMissing, match="psycopg2"):
            Registry(cfg).relation_tuple_manager()

    def test_registry_rejects_bare_string_typos(self):
        # 'Memory' / 'colummnar' must fail startup, not silently create
        # an empty sqlite file and deny every existing tuple
        from keto_tpu.config import Config
        from keto_tpu.registry import Registry

        for typo in ("Memory", "colummnar", "sqlite:/db"):
            cfg = Config({"dsn": typo, "namespaces": []}, validate=False)
            with pytest.raises(ValueError, match="unsupported DSN"):
                Registry(cfg).relation_tuple_manager()


class TestGenericPersisterOnSqlite:
    """SQLPersister driven through the generic path (explicit dialect
    object, prep shim, rowcount change-detection) — the same code a
    network dialect would exercise, on the one live engine."""

    def test_full_crud_round_trip(self):
        from keto_tpu.ketoapi import RelationQuery, RelationTuple

        p = SQLPersister("memory", dialect=SQLiteDialect())
        t = RelationTuple.from_string("videos:/cats/1.mp4#view@alice")
        p.write_relation_tuples([t])
        assert p.relation_tuple_exists(t)
        v1 = p.version()
        # idempotent re-insert must not bump the version (rowcount path)
        p.write_relation_tuples([t])
        assert p.version() == v1
        got, _ = p.get_relation_tuples(RelationQuery(namespace="videos"))
        assert got == [t]
        p.delete_relation_tuples([t])
        assert not p.relation_tuple_exists(t)
        assert p.version() == v1 + 1
        p.close()


class TestTransientClassification:
    """SQLSTATE/errno-first transient predicates (VERDICT r4 weak #7:
    string matching was the wrong signal space for server dialects)."""

    def test_postgres_sqlstate_codes(self):
        from keto_tpu.storage.dialect import PostgresDialect

        d = PostgresDialect()

        def err(code):
            e = Exception("boom")
            e.pgcode = code
            return e

        # class 08 (connection), explicit retryables
        for code in ("08006", "08001", "57P03", "53300", "40001", "40P01"):
            assert d.is_transient(err(code)), code
        # syntax error / undefined table / unique violation: permanent
        for code in ("42601", "42P01", "23505"):
            assert not d.is_transient(err(code)), code

    def test_postgres_connect_failures_fall_back_to_message(self):
        from keto_tpu.storage.dialect import PostgresDialect

        d = PostgresDialect()
        assert d.is_transient(Exception("connection refused"))
        assert not d.is_transient(
            Exception("password authentication failed for user")
        )

    def test_mysql_errnos(self):
        from keto_tpu.storage.dialect import MySQLDialect

        # classification keys off pymysql's OWN exception types (module
        # check): a raw ConnectionRefusedError also has an int args[0]
        # (errno 111) and must not hit the MySQL errno table
        MySQLError = type(
            "OperationalError", (Exception,), {"__module__": "pymysql.err"}
        )
        d = MySQLDialect()
        for errno in (1040, 1205, 1213, 2002, 2003, 2006, 2013):
            assert d.is_transient(MySQLError(errno, "x")), errno
        for errno in (1064, 1061, 1062):
            assert not d.is_transient(MySQLError(errno, "x")), errno

    def test_mysql_socket_errors_are_transient(self):
        from keto_tpu.storage.dialect import MySQLDialect

        d = MySQLDialect()
        assert d.is_transient(ConnectionRefusedError(111, "refused"))
        assert d.is_transient(TimeoutError("timed out"))
        assert not d.is_transient(Exception(1064, "not a pymysql type"))


class TestPrepQuoteAwareness:
    def test_literal_question_mark_survives(self):
        from keto_tpu.storage.dialect import PostgresDialect

        got = PostgresDialect().prep(
            "SELECT 1 FROM t WHERE note = 'why?' AND name = ?"
        )
        assert got == "SELECT 1 FROM t WHERE note = 'why?' AND name = %s"

    def test_escaped_quote_does_not_flip_parity(self):
        from keto_tpu.storage.dialect import PostgresDialect

        got = PostgresDialect().prep(
            "SELECT 1 FROM t WHERE note = 'it''s ok?' AND name = ?"
        )
        assert got == "SELECT 1 FROM t WHERE note = 'it''s ok?' AND name = %s"
