"""Tests for public API types and encodings.

Mirrors the reference's ketoapi tests (enc_string round-trips, URL-query
error cases from ketoapi/enc_url_query.go, subject exclusivity)."""

import pytest

from keto_tpu import errors, ketoapi
from keto_tpu.ketoapi import (
    RelationQuery,
    RelationTuple,
    SubjectSet,
    Tree,
    TreeNodeType,
    subject_from_string,
)


class TestStringEncoding:
    def test_subject_id_round_trip(self):
        t = RelationTuple.from_string("videos:/cats/1.mp4#view@felix")
        assert t.namespace == "videos"
        assert t.object == "/cats/1.mp4"
        assert t.relation == "view"
        assert t.subject_id == "felix"
        assert t.subject_set is None
        assert str(t) == "videos:/cats/1.mp4#view@felix"

    def test_subject_set_round_trip(self):
        s = "videos:/cats/1.mp4#view@(videos:/cats#owner)"
        t = RelationTuple.from_string(s)
        assert t.subject_set == SubjectSet("videos", "/cats", "owner")
        assert str(t) == s

    def test_subject_set_without_parens(self):
        t = RelationTuple.from_string("n:o#r@x:y#z")
        assert t.subject_set == SubjectSet("x", "y", "z")
        # canonical form always adds parens
        assert str(t) == "n:o#r@(x:y#z)"

    @pytest.mark.parametrize(
        "bad",
        ["no-colon#r@s", "n:no-hash@s", "n:o#no-at", ""],
    )
    def test_malformed(self, bad):
        with pytest.raises(errors.MalformedInputError):
            RelationTuple.from_string(bad)

    def test_empty_parts_allowed(self):
        # the reference parser does not reject empty components
        t = RelationTuple.from_string(":#@")
        assert t.namespace == "" and t.object == "" and t.relation == ""
        assert t.subject_id == ""

    def test_subject_parsing(self):
        assert subject_from_string("user") == "user"
        assert subject_from_string("(a:b#c)") == SubjectSet("a", "b", "c")
        assert subject_from_string("a:b#c") == SubjectSet("a", "b", "c")

    def test_wildcard_subject(self):
        t = RelationTuple.from_string("videos:/cats/1.mp4#view@*")
        assert t.subject_id == "*"


class TestURLQuery:
    def test_query_round_trip_subject_id(self):
        q = RelationQuery.make(namespace="n", object="o", relation="r", subject="s")
        v = q.to_url_query()
        assert v == {
            "namespace": "n",
            "object": "o",
            "relation": "r",
            "subject_id": "s",
        }
        q2 = RelationQuery.from_url_query(v)
        assert q2 == q

    def test_query_round_trip_subject_set(self):
        q = RelationQuery.make(namespace="n", subject=SubjectSet("a", "b", "c"))
        v = q.to_url_query()
        assert v["subject_set.namespace"] == "a"
        q2 = RelationQuery.from_url_query(v)
        assert q2.subject_set == SubjectSet("a", "b", "c")
        assert q2.namespace == "n" and q2.object is None

    def test_dropped_subject_key(self):
        with pytest.raises(errors.DroppedSubjectKeyError):
            RelationQuery.from_url_query({"subject": "s"})

    def test_duplicate_subject(self):
        with pytest.raises(errors.DuplicateSubjectError):
            RelationQuery.from_url_query(
                {"subject_id": "s", "subject_set.namespace": "n"}
            )

    def test_incomplete_subject_set(self):
        with pytest.raises(errors.IncompleteSubjectError):
            RelationQuery.from_url_query({"subject_set.namespace": "n"})

    def test_tuple_requires_subject(self):
        with pytest.raises(errors.NilSubjectError):
            RelationTuple.from_url_query({"namespace": "n", "object": "o", "relation": "r"})

    def test_tuple_requires_all_fields(self):
        with pytest.raises(errors.IncompleteTupleError):
            RelationTuple.from_url_query({"namespace": "n", "subject_id": "s"})


class TestJSON:
    def test_tuple_dict_round_trip(self):
        t = RelationTuple.make("n", "o", "r", SubjectSet("a", "b", "c"))
        assert RelationTuple.from_dict(t.to_dict()) == t

    def test_exclusive_subject(self):
        with pytest.raises(errors.DuplicateSubjectError):
            RelationTuple.from_dict(
                {
                    "namespace": "n",
                    "object": "o",
                    "relation": "r",
                    "subject_id": "s",
                    "subject_set": {"namespace": "a", "object": "b", "relation": "c"},
                }
            )

    def test_dropped_subject(self):
        with pytest.raises(errors.DroppedSubjectKeyError):
            RelationTuple.from_dict(
                {"namespace": "n", "object": "o", "relation": "r", "subject": "s"}
            )


class TestQueryMatch:
    def test_wildcards(self):
        t = RelationTuple.make("n", "o", "r", "s")
        assert RelationQuery().matches(t)
        assert RelationQuery(namespace="n").matches(t)
        assert not RelationQuery(namespace="m").matches(t)
        assert RelationQuery.make(subject="s").matches(t)
        assert not RelationQuery.make(subject=SubjectSet("n", "o", "r")).matches(t)


class TestTree:
    def test_round_trip(self):
        t = Tree(
            type=TreeNodeType.UNION,
            tuple=RelationTuple.make("n", "o", "r", "s"),
            children=[
                Tree(type=TreeNodeType.LEAF, tuple=RelationTuple.make("n", "o", "r", "x"))
            ],
        )
        assert Tree.from_dict(t.to_dict()).to_dict() == t.to_dict()

    def test_unknown_node_type(self):
        with pytest.raises(errors.UnknownNodeTypeError):
            Tree.from_dict({"type": "bogus"})

    def test_render(self):
        t = Tree(
            type=TreeNodeType.UNION,
            tuple=RelationTuple.make("n", "o", "r", "s"),
            children=[
                Tree(type=TreeNodeType.LEAF, tuple=RelationTuple.make("n", "o", "r", "x")),
                Tree(type=TreeNodeType.LEAF, tuple=RelationTuple.make("n", "o", "r", "y")),
            ],
        )
        out = str(t)
        assert out.startswith("or n:o#r@s")
        assert "∋ n:o#r@x" in out and "∋ n:o#r@y" in out
