"""Registry: the dependency-injection composition root.

Parity with driver.Registry (internal/driver/registry.go:23-52) and
RegistryDefault's lazy singletons (internal/driver/registry_default.go:
98-192): config + logger, tuple manager (chosen by DSN), check/expand
engines (TPU or host, chosen by `check.engine`), mapper, health state,
metrics, and the server handlers hang off one object that everything
receives. This is the plugin boundary named in the north star: swapping
`check.engine=tpu` for `host` here changes nothing above it.

DSN forms (ref: internal/driver/config/provider.go:187-193 aliases
"memory"; pop DSNs otherwise):
  - "memory"            -> in-process dict-of-arrays store (fast path)
  - "sqlite://<path>"   -> durable SQLite persister (runs migrations)
  - "sqlite://:memory:" -> in-memory SQLite (the reference's "memory")
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from . import __version__
from .config import Config
from .engine.reference import ReferenceEngine
from .errors import NamespaceNotFoundError
from .ketoapi import RelationQuery, RelationTuple
from .storage.definitions import DEFAULT_NETWORK
from .storage.memory import MemoryManager

logger = logging.getLogger("keto_tpu")


class ReadyState:
    """Event-compatible readiness flag with change notification.

    Health Watch streams park on `wait_change` (a Condition) instead of
    busy-polling, so idle watchers cost no CPU and wake immediately on a
    readiness transition (ref pushes on change; ADVICE round-1 flagged
    the 0.5s poll loop)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._flag = False
        self._gen = 0

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        with self._cond:
            if not self._flag:
                self._flag = True
                self._gen += 1
                self._cond.notify_all()

    def clear(self) -> None:
        with self._cond:
            if self._flag:
                self._flag = False
                self._gen += 1
                self._cond.notify_all()

    def state(self) -> tuple[bool, int]:
        with self._cond:
            return self._flag, self._gen

    def wait_change(self, gen: int, timeout: float) -> tuple[bool, int]:
        """Block until the generation moves past `gen` (or timeout, so
        stream handlers can re-check client liveness); returns the
        current (flag, generation)."""
        with self._cond:
            if self._gen == gen:
                self._cond.wait(timeout)
            return self._flag, self._gen


class Registry:
    """Composition root. Lazily builds every service exactly once."""

    def __init__(
        self,
        config: Optional[Config] = None,
        nid: str = DEFAULT_NETWORK,
        mesh=None,
        contextualizer=None,
    ):
        self.config = config or Config()
        self.nid = nid
        self.mesh = mesh
        self.version = __version__
        # operator platform pin: the container's sitecustomize can
        # force-select a remote TPU backend whose init BLOCKS while the
        # device/tunnel is unhealthy; `check.platform: cpu` keeps a
        # degraded deployment serving (exact host fallbacks either way)
        platform = self.config.get("check.platform")
        if platform:
            import jax

            try:  # the pin is a silent no-op once a backend exists —
                # surface that instead of letting the operator believe
                # the unhealthy backend was avoided
                from jax._src import xla_bridge

                if xla_bridge.backends_are_initialized():
                    import logging

                    logging.getLogger("keto_tpu").warning(
                        "check.platform=%r set after a JAX backend "
                        "initialized; the pin has no effect in this "
                        "process", platform,
                    )
            except ImportError:
                pass
            jax.config.update("jax_platforms", platform)
        self._lock = threading.RLock()
        self._manager = None
        self._engine = None
        # per-request tenancy (ketoctx.Contextualizer analog): nid_for()
        # derives the network from transport metadata; engines are cached
        # per nid (each network has its own device mirror)
        if contextualizer is None:
            from . import ketoctx

            contextualizer = ketoctx.from_config(self.config)
        self.contextualizer = contextualizer
        import collections

        self._nid_engines: "collections.OrderedDict[str, object]" = (
            collections.OrderedDict()
        )
        self._metrics = None
        self._tracer = None
        self._span_exporter = None
        self._span_exporter_built = False
        self._explain_limiter = None
        self._profiler = None
        self._flightrec = None
        self._workload = None
        self._workload_built = False
        self._scrubber = None
        self._closure_maintainer = None
        self._watch_hub = None
        self._check_cache = None
        self._check_cache_built = False
        self._breaker = None
        self._store_breaker = None
        # health: flipped by the daemon around serving
        # (ref: registry_default.go:98-112 healthx readiness checkers)
        self.ready = ReadyState()
        # drain flag: set by Daemon.stop for the shutdown grace window —
        # the admission gate (resilience.admit_check) sheds new checks
        # with a typed 429 while in-flight work completes
        self.draining = threading.Event()
        # replica serving group (api/replica.py), attached by the daemon
        # when serve.check.workers >= 2; the metrics listener's
        # GET /admin/replicas reads it (None = single-stack serving)
        self.replica_group = None
        # HA follower plane (api/follower.py), attached by the daemon
        # when follower.enabled; GET /admin/ha reads it (None on a
        # leader — ha_status() then reports the leader-side view)
        self.ha_plane = None
        self._follower_store = None

    # -- storage --------------------------------------------------------------

    def relation_tuple_manager(self):
        with self._lock:
            if self._manager is None:
                dsn = self.config.dsn
                if bool(self.config.get("follower.enabled", False)):
                    # HA follower daemon (api/follower.py): the store is
                    # a network-fed mirror of the LEADER's — versions
                    # pinned to the leader's commit versions, local
                    # writes refused with a typed 503. The DSN is
                    # ignored: this process never owns tuples. The RAW
                    # store reference is kept for the replication plane
                    # (apply_remote must bypass the health guard —
                    # replication is not request traffic).
                    from .api.follower import FollowerStore

                    self._manager = self._follower_store = FollowerStore()
                elif dsn == "memory":
                    self._manager = MemoryManager()
                elif dsn == "columnar":
                    # scale tier: numpy-column store (1e8-tuple ingest)
                    from .storage.columnar import ColumnarStore

                    self._manager = ColumnarStore()
                else:
                    # sqlite:// | postgres:// | cockroach:// | mysql://
                    # route through the STRICT dialect layer
                    # (storage/dialect.py): an unknown scheme, a missing
                    # driver, or a bare-string typo ('Memory') raises
                    # with the reason — failing startup beats silently
                    # serving an empty store from a fresh sqlite file
                    from .storage.sqlite import SQLPersister

                    self._manager = SQLPersister(
                        dsn,
                        legacy_namespaces=self.config.legacy_namespace_ids(),
                    )
                # span-per-store-op when tracing (ref: otel spans in every
                # persister method, relationtuples.go:203-205); the OTLP
                # endpoint alone also turns these on — an exported trace
                # without its store-op spans is missing its leaves
                if self.config.get("tracing.enabled", False) or self.config.get(
                    "observability.otlp.endpoint"
                ):
                    from .observability import TracedManager

                    self._manager = TracedManager(self._manager, self.tracer())
                # store health plane (storage/health.py): the OUTERMOST
                # wrapper — per-op timeouts on a bounded executor (SQL
                # dialects; the in-process dict stores cannot hang, so
                # they run inline) + the store-path circuit breaker
                # every consumer shares. When SQL dies: reads the mirror
                # covers degrade to bounded staleness, everything else
                # sheds a typed 503 — never wrong, never hung.
                if bool(self.config.get("store.health.enabled", True)):
                    from .storage.health import StoreHealthGuard

                    self._manager = StoreHealthGuard(
                        self._manager,
                        breaker=self.store_breaker(),
                        op_timeout_s=float(
                            self.config.get("store.op_timeout_ms", 1000)
                        ) / 1e3,
                        bulk_timeout_s=float(
                            self.config.get("store.bulk_timeout_ms", 120000)
                        ) / 1e3,
                        # in-process dict stores cannot hang — and the
                        # follower's network-fed mirror is one of them,
                        # whatever the (ignored) DSN says
                        use_executor=(
                            dsn not in ("memory", "columnar")
                            and self._follower_store is None
                        ),
                        metrics=self.metrics(),
                    )
            return self._manager

    def follower_store(self):
        """The RAW FollowerStore when this process is a follower
        (follower.enabled), else None. Raw = unwrapped by Traced/
        HealthGuard: the replication tail writes through this reference
        (apply_remote/bootstrap_replace are infrastructure, not request
        traffic — they must land even while the request-path breaker is
        open)."""
        self.relation_tuple_manager()  # ensure built
        return self._follower_store

    def ha_status(self) -> dict:
        """The /admin/ha document: the follower plane's status when one
        is attached, else the leader-side view (store version + watch
        tail are the ground truth followers replicate toward)."""
        if self.ha_plane is not None:
            return self.ha_plane.status()
        from .errors import StoreUnavailableError

        try:
            version = self.relation_tuple_manager().version(nid=self.nid)
        except StoreUnavailableError:
            version = None
        status: dict = {
            "role": "leader",
            "nid": self.nid,
            "store_version": version,
        }
        hub = self._watch_hub
        if hub is not None:
            status["watch_heartbeat_s"] = hub.heartbeat_s
        breaker = self._store_breaker
        if breaker is not None:
            status["store_breaker"] = breaker.state
        return status

    # -- engines --------------------------------------------------------------

    # client-supplied tenant ids are untrusted input: they become store
    # scopes, engine-cache keys, and checkpoint file names — constrain
    # the alphabet (no path separators) and length before any of that
    _NID_RE = __import__("re").compile(r"^[A-Za-z0-9._-]{1,128}$")

    def nid_for(self, metadata=None) -> str:
        """The network id for one request (ref: Contextualizer.Network,
        /root/reference/ketoctx/contextualizer.go:12-19); metadata is the
        transport's header/metadata mapping. A malformed tenant id is a
        client error (400), never a silent fallback to the default
        network (that would serve another tenant's data)."""
        if self.contextualizer is None or metadata is None:
            return self.nid
        nid = self.contextualizer.network(metadata, self.nid)
        if nid != self.nid and not self._NID_RE.match(nid):
            from .errors import MalformedInputError

            raise MalformedInputError(debug=f"invalid network id {nid!r}")
        return nid

    def check_engine(self, nid: Optional[str] = None):
        """The configured check engine for one network; `check.engine`
        selects `tpu` (batched device kernel + exact host fallback) or
        `host` (pure reference semantics). Engines are cached per nid
        with an LRU bound (`tenancy.max_networks`) so arbitrary tenant
        ids can't grow memory without limit; evicted engines flush any
        pending mirror checkpoint and are rebuilt on demand."""
        if nid is None or nid == self.nid:
            with self._lock:
                if self._engine is None:
                    self._engine = self._build_engine(self.nid)
                return self._engine
        evicted: list = []
        with self._lock:
            engine = self._nid_engines.pop(nid, None)
            if engine is None:
                engine = self._build_engine(nid)
                cap = int(self.config.get("tenancy.max_networks", 64))
                while len(self._nid_engines) >= max(cap, 1):
                    evicted.append(self._nid_engines.popitem(last=False)[1])
            self._nid_engines[nid] = engine  # (re-)insert at MRU
        if evicted:
            # flush EVERY evicted engine's pending checkpoint, off the
            # request thread (the compressed write can take seconds)
            def _flush_evicted(engines=tuple(evicted)):
                for e in engines:
                    # end the push-refresh thread first: its bound-method
                    # target would pin the evicted engine in memory
                    stop = getattr(e, "stop_push_refresh", None)
                    if stop is not None:
                        stop()
                    flush = getattr(e, "flush_checkpoints", None)
                    if flush is not None:
                        flush()

            t = threading.Thread(
                target=_flush_evicted, name="keto-evict-flush", daemon=True
            )
            t.start()
        return engine

    def flush_checkpoints(self) -> None:
        """Flush pending device-mirror checkpoints for EVERY cached
        engine (default network + all tenants); the daemon calls this on
        graceful shutdown. A failing write (full disk, revoked mount)
        must not abort the drain: the checkpoint is a warm-restart
        optimization — the store is the durability — so each failure is
        logged + counted and the remaining engines still flush."""
        with self._lock:
            engines = list(self._nid_engines.values())
            if self._engine is not None:
                engines.append(self._engine)
        for engine in engines:
            flush = getattr(engine, "flush_checkpoints", None)
            if flush is None:
                continue
            try:
                flush()
            except Exception:  # noqa: BLE001 — shutdown must complete
                logger.warning(
                    "mirror checkpoint flush failed for nid=%s "
                    "(cold start will rebuild from the store)",
                    getattr(engine, "nid", "?"), exc_info=True,
                )
                self.metrics().checkpoint_write_failures_total.inc()

    def _build_engine(self, nid: str):
        kind = self.config.get("check.engine", "tpu")
        manager = self.relation_tuple_manager()
        if kind == "tpu":
            from .engine.tpu_engine import TPUCheckEngine

            return TPUCheckEngine(
                manager, self.config, nid=nid, mesh=self.mesh,
                metrics=self.metrics(), tracer=self.tracer(),
                frontier_cap=int(
                    self.config.get("check.frontier_cap", 1 << 14)
                ),
                auto_frontier=bool(
                    self.config.get("check.auto_frontier", True)
                ),
                flightrec=self.flight_recorder(),
            )
        if kind == "host":
            return _HostEngineFacade(
                ReferenceEngine(manager, self.config), nid,
                metrics=self.metrics(),
            )
        raise ValueError(f"unknown check.engine: {kind!r}")

    def expand_engine(self, nid: Optional[str] = None):
        return self.check_engine(nid)

    # -- watch subsystem ------------------------------------------------------

    def watch_hub(self):
        """The process-wide changelog streaming hub (keto_tpu/watch):
        registers itself as the store's post-commit write listener and
        trim guard, and push-invalidates cached engines' device mirrors
        on every commit (delta refresh becomes event-driven instead of
        per-request changes_since polling)."""
        with self._lock:
            if self._watch_hub is None:
                from .watch import WatchHub

                # in-band heartbeats are OPT-IN (an explicitly set
                # watch.heartbeat_s): the HA follower tail needs them
                # for liveness + idle version discovery, while default
                # single-daemon streams keep the pre-HA event mix
                hb = self.config.get("watch.heartbeat_s")
                self._watch_hub = WatchHub(
                    self.relation_tuple_manager(),
                    poll_interval=float(
                        self.config.get("watch.poll_interval", 0.25)
                    ),
                    buffer=int(self.config.get("watch.buffer", 256)),
                    metrics=self.metrics(),
                    heartbeat_s=float(hb) if hb is not None else None,
                )
                self._watch_hub.add_commit_listener(self._push_invalidate)
            return self._watch_hub

    def _push_invalidate(self, nid: str) -> None:
        """Hub commit listener: poke the ALREADY-BUILT engine for `nid`
        (never builds one — a tenant nobody queries must not get a device
        mirror just because someone wrote to it) and the serve-side
        check cache's invalidation thread."""
        from . import faults as _faults

        # crash point (keto_tpu/faults.py): committed + hub-notified but
        # the engine/cache pokes never ran — the restarted process must
        # converge from the durable store alone (it does: invalidation
        # is hygiene, the per-request version gate is the correctness)
        _faults.inject("cache_invalidation")
        with self._lock:
            engine = (
                self._engine if nid == self.nid else self._nid_engines.get(nid)
            )
            cache = self._check_cache
        if cache is not None:
            cache.notify_commit(nid)
        if engine is None:
            return
        poke = getattr(engine, "notify_write", None)
        if poke is not None:
            poke()

    def check_cache(self):
        """The serve-side snaptoken-consistent check cache
        (api/check_cache.py), or None when `check.cache.enabled` is
        false. Consulted by all three transports before the batcher;
        invalidated through the watch hub's commit listeners (wired in
        _push_invalidate) — correctness, however, rides the per-request
        store-version gate, never invalidation delivery.

        Lock-free after the first call (every check consults this): the
        built flag is written LAST under the lock, so a reader seeing it
        set also sees the cache reference."""
        if self._check_cache_built:
            return self._check_cache
        with self._lock:
            if not self._check_cache_built:
                if bool(self.config.get("check.cache.enabled", True)):
                    from .api.check_cache import CheckCache

                    self._check_cache = CheckCache(
                        self.relation_tuple_manager(),
                        self.config,
                        max_entries=int(
                            self.config.get("check.cache.max_entries", 65536)
                        ),
                        ttl_s=float(self.config.get("check.cache.ttl_s", 0.0)),
                        metrics=self.metrics(),
                    )
                self._check_cache_built = True
            return self._check_cache

    def close_check_cache(self) -> None:
        """End the check cache's invalidation thread (daemon shutdown);
        safe when the cache was never built or is disabled."""
        with self._lock:
            cache = self._check_cache
        if cache is not None:
            cache.close()

    def namespace_manager(self):
        return self.config.namespace_manager()

    # -- namespace validation (the Mapper's role) -----------------------------

    def validate_namespaces(self, *objs) -> None:
        """Every namespace mentioned by a tuple/query must be configured —
        the reference enforces this inside Mapper.FromTuple/FromQuery via
        NamespaceManager.GetNamespaceByName (internal/relationtuple/
        uuid_mapping.go:70-81); raises NamespaceNotFoundError."""
        nm = self.namespace_manager()
        for o in objs:
            if o is None:
                continue
            names = []
            if isinstance(o, (RelationTuple, RelationQuery)):
                if o.namespace is not None:
                    names.append(o.namespace)
                if o.subject_set is not None:
                    names.append(o.subject_set.namespace)
            else:  # SubjectSet
                names.append(o.namespace)
            for name in names:
                nm.get_namespace_by_name(name)  # raises if unknown

    # -- observability --------------------------------------------------------

    def metrics(self):
        with self._lock:
            if self._metrics is None:
                from .observability import Metrics

                self._metrics = Metrics()
            return self._metrics

    def tracer(self):
        with self._lock:
            if self._tracer is None:
                from .observability import build_tracer

                self._tracer = build_tracer(
                    self.config, exporter=self.span_exporter()
                )
            return self._tracer

    def span_exporter(self):
        """The process-wide OTLP span exporter
        (observability.SpanExporter), or None when
        `observability.otlp.endpoint` is unset. Setting the endpoint is
        the opt-in: the tracer then records spans AND exports them —
        bounded queue, background batched POSTs, drop counters — so the
        trace_id a client sent as `traceparent` leaves the process as a
        real multi-span OTLP trace. The daemon flushes + closes it on
        stop."""
        with self._lock:
            if not self._span_exporter_built:
                endpoint = self.config.get("observability.otlp.endpoint")
                if endpoint:
                    from .observability import SpanExporter

                    self._span_exporter = SpanExporter(
                        str(endpoint),
                        metrics=self.metrics(),
                        queue_size=int(
                            self.config.get("observability.otlp.queue", 2048)
                        ),
                        flush_interval_s=float(
                            self.config.get(
                                "observability.otlp.flush_interval_ms", 200
                            )
                        ) / 1e3,
                        service_name=str(
                            self.config.get(
                                "tracing.service_name", "keto_tpu"
                            )
                        ),
                    )
                self._span_exporter_built = True
            return self._span_exporter

    def explain_limiter(self):
        """The explain plane's token bucket (resilience.TokenBucket,
        `explain.max_per_s`): one process-wide bucket shared by every
        transport, so the cache-bypassing witness-re-walk slow path is
        rate-bounded no matter which plane the requests arrive on."""
        with self._lock:
            if self._explain_limiter is None:
                from .resilience import (
                    DEFAULT_EXPLAIN_MAX_PER_S,
                    TokenBucket,
                )

                rate = float(
                    self.config.get(
                        "explain.max_per_s", DEFAULT_EXPLAIN_MAX_PER_S
                    )
                )
                self._explain_limiter = TokenBucket(rate)
            return self._explain_limiter

    def circuit_breaker(self):
        """The process-wide device-path circuit breaker
        (resilience.CircuitBreaker), shared by both batching planes so
        device health is judged from all traffic. Always built (the
        defaults are harmless when the device is healthy); tuned via
        serve.check.breaker.{threshold,cooldown_s}."""
        with self._lock:
            if self._breaker is None:
                from .resilience import CircuitBreaker

                self._breaker = CircuitBreaker(
                    threshold=int(
                        self.config.get("serve.check.breaker.threshold", 5)
                    ),
                    cooldown_s=float(
                        self.config.get("serve.check.breaker.cooldown_s", 5.0)
                    ),
                    metrics=self.metrics(),
                )
            return self._breaker

    def store_breaker(self):
        """The process-wide STORE-path circuit breaker (the twin of
        circuit_breaker(), which judges the DEVICE path): consecutive
        store read failures/timeouts trip it; while open, every store
        op fails fast (typed 503) and the serve path degrades onto the
        device mirror at its covered version. Tuned via
        store.breaker.{threshold,cooldown_s}; exported as
        keto_tpu_store_breaker_state."""
        with self._lock:
            if self._store_breaker is None:
                from .resilience import CircuitBreaker
                from .storage.health import StoreBreakerMetrics

                self._store_breaker = CircuitBreaker(
                    threshold=int(
                        self.config.get("store.breaker.threshold", 5)
                    ),
                    cooldown_s=float(
                        self.config.get("store.breaker.cooldown_s", 5.0)
                    ),
                    metrics=StoreBreakerMetrics(self.metrics()),
                )
            return self._store_breaker

    def mirror_scrubber(self):
        """The anti-entropy device-mirror scrubber (engine/scrub.py):
        one background singleton incrementally checksumming every built
        engine's device tables against the host truth at the mirror's
        covered version. `scrub.{enabled,interval_s,slice_rows}`
        configure it; the daemon starts/stops the loop around serving,
        and `GET/POST /admin/scrub` on the metrics listener read state /
        trigger a full pass on demand."""
        with self._lock:
            if self._scrubber is None:
                from .engine.scrub import MirrorScrubber

                self._scrubber = MirrorScrubber(
                    self,
                    enabled=bool(self.config.get("scrub.enabled", False)),
                    interval_s=float(self.config.get("scrub.interval_s", 30.0)),
                    slice_rows=int(self.config.get("scrub.slice_rows", 1 << 16)),
                    metrics=self.metrics(),
                )
            return self._scrubber

    def closure_maintainer(self):
        """The Leopard-index maintenance plane (keto_tpu/closure): one
        background tailer keeping every built engine's closure index
        synced from the Watch changelog and re-powering it off the
        request path. The daemon starts/stops it around serving when
        `closure.enabled`; correctness never depends on it (every
        closure answer is version-gated at submit)."""
        with self._lock:
            if self._closure_maintainer is None:
                from .closure import ClosureMaintainer

                self._closure_maintainer = ClosureMaintainer(
                    self,
                    poll_interval=float(
                        self.config.get("watch.poll_interval", 0.25)
                    ),
                )
            return self._closure_maintainer

    def profiler(self):
        """The process-wide on-demand capture session (profiling.py),
        toggled live through the metrics listener's /admin/profiling
        endpoint — no restart to profile a running serve."""
        with self._lock:
            if self._profiler is None:
                from .profiling import Profiler

                self._profiler = Profiler()
            return self._profiler

    def flight_recorder(self):
        """The process-wide launch flight recorder
        (observability.FlightRecorder): ONE bounded ring shared by every
        engine and both batching planes, so `GET /admin/flightrec` and
        the failure auto-dumps see all launches in arrival order.
        `observability.flightrec.{enabled,capacity}` configure it; ids
        keep advancing when disabled so logs stay correlatable."""
        with self._lock:
            if self._flightrec is None:
                from .observability import FlightRecorder

                self._flightrec = FlightRecorder(
                    enabled=bool(
                        self.config.get("observability.flightrec.enabled", True)
                    ),
                    capacity=int(
                        self.config.get("observability.flightrec.capacity", 256)
                    ),
                    metrics=self.metrics(),
                )
                # ambient device-path health stamped onto every entry;
                # attribute reads only (no locks) — a provider must never
                # contend with the serve path
                self._flightrec.context_providers.append(
                    self._flightrec_context
                )
            return self._flightrec

    def _flightrec_context(self) -> dict:
        """Breaker + armed-faults state for flight-recorder entries.
        Reads the already-built breaker reference (never builds one —
        recording must not construct services)."""
        from . import faults as _faults

        breaker = self._breaker
        ctx: dict = {
            "faults": sorted(_faults.armed_names()),
        }
        if breaker is not None:
            # .state is a property — calling its str return value raised
            # and (because record() guards providers) silently dropped
            # the whole context from every entry a breaker-ful process
            # recorded
            ctx["breaker"] = breaker.state
        store_breaker = self._store_breaker
        if store_breaker is not None:
            ctx["store_breaker"] = store_breaker.state
        return ctx

    def workload_observatory(self):
        """The process-wide workload observatory + SLO plane
        (observability_workload.WorkloadObservatory). ONE instance
        shared by every transport: per-(nid, relation) accounting and
        the hot-key sketches feed from the check serve gate, the SLO
        engine feeds from finish_request_telemetry. `workload.enabled`
        and `slo.enabled` gate the two halves internally (the object
        always exists, so the A/B off arm is one attribute test).

        Lock-free after the first call (every finished request consults
        this): the built flag is written LAST under the lock, so a
        reader seeing it set also sees the observatory reference — the
        check cache's publication pattern."""
        if self._workload_built:
            return self._workload
        with self._lock:
            if not self._workload_built:
                from .observability_workload import build_observatory

                self._workload = build_observatory(
                    self.config,
                    metrics=self.metrics(),
                    staleness_probe=self._mirror_staleness_age,
                )
                self._workload_built = True
            return self._workload

    def _mirror_staleness_age(self):
        """Max mirror staleness age (seconds) across ALREADY-BUILT
        engines, for the SLO max_staleness_s objective — never builds
        an engine (sampled once per SLO eval tick; a probe must not
        construct device mirrors), returns None when no built engine
        reports one (host facade, nothing built yet)."""
        worst = None
        for eng in self.built_engines().values():
            probe = getattr(eng, "mirror_staleness_age_s", None)
            if probe is None:
                continue
            try:
                age = probe()
            # ketolint: allow[typed-error] reason=SLO staleness probe isolation: one engine's introspection failure must cost that engine's sample, never the whole evaluation tick (the probe runs inside the SLO engine's lock-held tick path)
            except Exception:  # pragma: no cover - defensive isolation
                continue
            # a NEVER-synced engine reports inf — that is "no sync has
            # happened yet" (cold start, first batch still compiling),
            # not "the mirror is infinitely stale": nothing has been
            # served from it. Counting it latched a spurious
            # max_staleness_s fast burn on every cold start.
            if age is None or age == float("inf"):
                continue
            if worst is None or age > worst:
                worst = age
        return worst

    def built_engines(self) -> dict:
        """Engines that already exist (default network + tenant LRU),
        WITHOUT building any — the admin plane reads state, it must not
        instantiate device mirrors."""
        with self._lock:
            out: dict = {}
            if self._engine is not None:
                out[self.nid] = self._engine
            out.update(self._nid_engines)
            return out


class _HostEngineFacade:
    """Adapts ReferenceEngine to the engine surface the RPC layer uses
    (check_batch / check_is_member / check_relation_tuple / expand)."""

    def __init__(self, reference: ReferenceEngine, nid: str, metrics=None):
        self.reference = reference
        self.nid = nid
        self.stats = {"device_checks": 0, "host_checks": 0, "snapshot_builds": 0}
        self.metrics = metrics

    def check_is_member(self, r, max_depth: int = 0) -> bool:
        res = self.check_relation_tuple(r, max_depth)
        if res.error is not None:
            raise res.error
        from .engine.definitions import Membership

        return res.membership == Membership.IS_MEMBER

    def check_relation_tuple(self, r, max_depth: int = 0):
        return self.reference.check_relation_tuple(r, max_depth, self.nid)

    def check_batch(self, tuples, max_depth: int = 0):
        self.stats["host_checks"] += len(tuples)
        if self.metrics is not None and tuples:
            self.metrics.check_batch_size.observe(len(tuples))
            self.metrics.checks_total.labels("host").inc(len(tuples))
        return [self.check_relation_tuple(t, max_depth) for t in tuples]

    def explain_check(self, t, max_depth: int = 0, rt=None):
        """Explain on the host engine: verdict and witness come from the
        same walk family, tier is always `host` (there is no device to
        differ from, so witness_consistent is the walk agreeing with
        the pruned check — still a real differential on cyclic graphs).
        `rt` accepted for surface parity; the host walk records no
        engine stages or launch ids."""
        from .engine.explain import base_trace

        res = self.check_relation_tuple(t, max_depth)
        allowed = res.error is None and res.allowed
        wx = self.reference._complete_checker().explain_check(
            t, max_depth, self.nid
        )
        trace = base_trace(
            allowed=allowed,
            tier="host",
            version=self.reference.manager.version(nid=self.nid),
            max_depth=wx.get("max_depth"),
            witness=wx.get("witness", []) if allowed else [],
            exhaustion=None if allowed else wx.get("exhaustion"),
            witness_verdict=wx["allowed"],
            witness_consistent=(
                res.error is None and wx["allowed"] == allowed
            ),
        )
        if res.error is not None:
            trace["error"] = str(res.error)
        return res, trace

    def expand(self, subject, max_depth: int = 0):
        return self.reference.expand(subject, max_depth, self.nid)

    def list_objects(
        self, namespace, relation, subject, max_depth: int = 0,
        page_size: int = 100, page_token: str = "",
    ):
        from .engine.definitions import paginate_names

        self.stats["host_list_objects"] = (
            self.stats.get("host_list_objects", 0) + 1
        )
        return paginate_names(
            self.reference.list_objects(
                namespace, relation, subject, max_depth, self.nid
            ),
            page_size, page_token,
        )

    def list_subjects(
        self, namespace, obj, relation, max_depth: int = 0,
        page_size: int = 100, page_token: str = "",
    ):
        from .engine.definitions import paginate_names

        self.stats["host_list_subjects"] = (
            self.stats.get("host_list_subjects", 0) + 1
        )
        return paginate_names(
            self.reference.list_subjects(
                namespace, obj, relation, max_depth, self.nid
            ),
            page_size, page_token,
        )

    def invalidate(self) -> None:
        pass
