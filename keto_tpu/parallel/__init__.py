"""Multi-chip parallelism: edge-sharded graph mirror + SPMD check kernel.

The reference scales by pointing N stateless server replicas at one SQL
database (SURVEY.md §2.11); the TPU-native analog is ONE logical engine
whose edge tables are sharded over a `jax.sharding.Mesh` and whose BFS
steps merge per-shard results with ICI collectives (psum for membership,
all_gather for frontier candidates).
"""

from .sharding import ShardedSnapshot, build_sharded_snapshot, default_mesh
from .kernel import sharded_check_kernel

__all__ = [
    "ShardedSnapshot",
    "build_sharded_snapshot",
    "default_mesh",
    "sharded_check_kernel",
]
