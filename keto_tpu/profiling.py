"""Env/config-driven serve profiling.

The reference wraps its entire process in `profilex.Profile()`
(/root/reference/main.go:24): the PROFILING env var ("cpu" | "mem")
turns on a profiler whose report is written when the process stops, so
an operator can profile a production serve without code changes. The
Python analog:

  - "cpu": cProfile around the serve loop; a pstats dump is written on
    stop (readable with `python -m pstats <file>`)
  - "mem": tracemalloc; the top-25 allocation sites by size are written
    as text on stop

Source of truth: the `profiling` config key (embedx parity —
config_schema.json) with the KETO_PROFILING env var taking precedence,
mirroring profilex's env-only contract. Output path: KETO_PROFILE_PATH
or ./keto_<mode>.pprof-like defaults.
"""

from __future__ import annotations

import os
from contextlib import contextmanager


@contextmanager
def profiled(mode: str | None, path: str | None = None):
    """Context manager running the serve loop under the selected
    profiler; no-op for falsy/unknown modes (same forgiving contract as
    profilex: an operator typo must not stop the server)."""
    mode = (os.environ.get("KETO_PROFILING") or mode or "").strip().lower()
    if mode == "cpu":
        import cProfile

        out = path or os.environ.get("KETO_PROFILE_PATH") or "keto_cpu.pstats"
        prof = cProfile.Profile()
        prof.enable()
        try:
            yield
        finally:
            prof.disable()
            prof.dump_stats(out)
    elif mode == "mem":
        import tracemalloc

        out = path or os.environ.get("KETO_PROFILE_PATH") or "keto_mem.txt"
        tracemalloc.start(25)
        try:
            yield
        finally:
            snap = tracemalloc.take_snapshot()
            tracemalloc.stop()
            stats = snap.statistics("lineno")[:25]
            with open(out, "w") as f:
                f.write("\n".join(str(s) for s in stats) + "\n")
    else:
        yield
