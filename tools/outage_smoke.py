#!/usr/bin/env python
"""Store-outage smoke: kill-store-under-live-load cycles, CPU-runnable,
CI-wired — the §5n degradation plane's executable evidence.

A real daemon serves a file-backed sqlite store (TPU-engine code path
pinned to CPU) under continuous live load: checker threads on gRPC,
a writer on the gRPC write plane, and a watch subscriber. Each cycle
arms the process-wide ``store_outage`` fault (keto_tpu/faults.py) —
every store op fails — and asserts the degradation contract:

  1. NEVER WRONG — every answered check is compared against the host
     oracle evaluated at the answer's STAMPED snaptoken (the client-side
     write ledger reconstructs the store content at any version, like
     tools/check_cache_correctness.py's window replay). Degraded
     answers carry the mirror's covered version as their token — the
     staleness bound is explicit — and must equal the oracle there.
     Zero wrong answers is the pass bar, outage or not.
  2. NEVER HUNG — requests during the outage answer promptly with
     either a degraded 200 or a typed 503 (`store_unavailable` /
     UNAVAILABLE); no request exceeds its wait bound, and the
     post-run thread census is clean (all load threads joined, no
     thread-count growth across cycles from wedged store ops).
  3. WRITES SHED TYPED — while the store breaker is open, writes
     return typed 503s with Retry-After, byte/code-identical across
     the REST and gRPC write planes; a snaptoken demanding a version
     newer than the mirror covers is a typed 503 on REST, sync-gRPC,
     AND aio-gRPC with identical details (tri-plane parity).
  4. WATCH DEGRADES IN-BAND — the subscriber receives exactly one
     DEGRADED marker per outage episode instead of a silent stall, and
     change delivery resumes from the same cursor after recovery.
  5. RECOVERY — after the fault clears, read traffic probes the
     breaker closed (half-open probe read), writes flow again, and
     read-your-writes holds (a fresh write's token check answers True).
     The whole closed -> open -> half_open -> closed story is scraped
     from /metrics/prometheus (keto_tpu_store_breaker_state /
     _transitions_total).

``--artifact out.json`` commits the full per-cycle record
(OUTAGE_SMOKE_r15.json). ``--ab`` runs the healthy-path A/B instead:
two identical daemons (store.health on vs off) measured in alternating
windows on the served check leg — the plumbing must cost < 2%
(STOREHEALTH_AB_r15.json). Exit 0 prints one JSON summary line; any
violation exits 1.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FIXTURE = [
    "files:doc0#owner@u0",
    "files:doc1#owner@u1",
    "files:doc#view@(groups:g#member)",
    "groups:g#member@alice",
]
# (tuple string) pool the checkers cycle through — direct hits, misses,
# and subject-set indirection, plus the writer's freshly-written docs
QUERIES = [
    "files:doc0#owner@u0",
    "files:doc1#owner@u0",
    "files:doc#view@alice",
    "files:doc#view@u1",
]


def build_daemon(base_dir: str, health: bool = True, dsn: str = ""):
    from keto_tpu.api.daemon import Daemon
    from keto_tpu.config import Config
    from keto_tpu.ketoapi import RelationTuple
    from keto_tpu.namespace import Namespace
    from keto_tpu.registry import Registry

    cfg = Config({
        "dsn": dsn or f"sqlite://{base_dir}/outage.db",
        "check": {"engine": "tpu"},
        "store": {
            "health": {"enabled": health},
            "op_timeout_ms": 500,
            "breaker": {"threshold": 3, "cooldown_s": 0.3},
        },
        "watch": {"poll_interval": 0.05, "heartbeat_s": 1.0},
        "serve": {
            "read": {
                "host": "127.0.0.1", "port": 0,
                "grpc": {"host": "127.0.0.1", "port": 0, "aio": True},
            },
            "write": {"host": "127.0.0.1", "port": 0},
            "metrics": {"host": "127.0.0.1", "port": 0},
        },
    })
    cfg.set_namespaces([Namespace(name="files"), Namespace(name="groups")])
    reg = Registry(cfg)
    reg.relation_tuple_manager().write_relation_tuples(
        [RelationTuple.from_string(s) for s in FIXTURE]
    )
    # warm the mirror + XLA before any outage
    from keto_tpu.ketoapi import RelationTuple as RT

    reg.check_engine().check_batch([RT.from_string(QUERIES[0])])
    d = Daemon(reg)
    d.start()
    return d


# -- client-side oracle ledger -------------------------------------------------


class Ledger:
    """The client's exact knowledge of the store: fixture at v1, plus
    every ACKED write's (version, inserts). Reconstructs content at any
    version and evaluates the host oracle there — the referee every
    stamped-snaptoken answer is judged by."""

    def __init__(self):
        from keto_tpu.ketoapi import RelationTuple

        self._rt = RelationTuple
        self._mu = threading.Lock()
        # fixture committed as ONE batch -> version 1
        self.writes: dict[int, list[str]] = {1: list(FIXTURE)}
        self._oracle_cache: dict[int, object] = {}

    def ack(self, version: int, tuples: list[str]) -> None:
        with self._mu:
            self.writes.setdefault(version, []).extend(tuples)
            # content changed at `version`: drop any cached engine at or
            # past it (tokens are monotone, so this is rare and cheap)
            for v in [v for v in self._oracle_cache if v >= version]:
                del self._oracle_cache[v]

    def oracle_allowed(self, tuple_s: str, version: int) -> bool:
        from keto_tpu.config import Config
        from keto_tpu.engine.reference import ReferenceEngine
        from keto_tpu.namespace import Namespace
        from keto_tpu.storage.memory import MemoryManager

        with self._mu:
            eng = self._oracle_cache.get(version)
            if eng is None:
                m = MemoryManager()
                for v in sorted(self.writes):
                    if v > version:
                        break
                    m.write_relation_tuples(
                        [self._rt.from_string(s) for s in self.writes[v]]
                    )
                cfg = Config({"dsn": "memory"})
                cfg.set_namespaces(
                    [Namespace(name="files"), Namespace(name="groups")]
                )
                eng = ReferenceEngine(m, cfg)
                self._oracle_cache[version] = eng
            res = eng.check_relation_tuple(self._rt.from_string(tuple_s), 0)
        return res.error is None and res.allowed


def parse_version(token: str) -> int:
    return int(token.rsplit("_", 1)[1])


# -- load threads --------------------------------------------------------------


class CheckLoad:
    """Continuous checks on one gRPC channel; every answered check is
    recorded with its stamped snaptoken for the oracle audit; typed
    unavailability is counted, anything else is a violation."""

    def __init__(self, port: int, queries):
        import grpc as _grpc

        from keto_tpu.api.client import ReadClient

        self._client = ReadClient(
            _grpc.insecure_channel(f"127.0.0.1:{port}")
        )
        self.queries = list(queries)
        self.answers: list[tuple[str, bool, int]] = []
        self.typed_unavailable = 0
        self.other_errors: list[str] = []
        self.slow: list[float] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        import grpc as _grpc

        from keto_tpu.ketoapi import RelationTuple

        i = 0
        while not self._stop.is_set():
            q = self.queries[i % len(self.queries)]
            i += 1
            t0 = time.monotonic()
            try:
                allowed, token = self._client.check_with_token(
                    RelationTuple.from_string(q), timeout=5
                )
                self.answers.append((q, allowed, parse_version(token)))
            except _grpc.RpcError as e:
                code = e.code()
                if code in (
                    _grpc.StatusCode.UNAVAILABLE,
                    _grpc.StatusCode.RESOURCE_EXHAUSTED,
                ):
                    self.typed_unavailable += 1
                else:
                    self.other_errors.append(f"{code}: {e.details()}")
            except Exception as e:  # noqa: BLE001 — recorded as violation
                self.other_errors.append(f"{type(e).__name__}: {e}")
            dt = time.monotonic() - t0
            # the hard hang detector is the 5s client deadline (a hung
            # request surfaces as DEADLINE_EXCEEDED -> other_errors);
            # this records near-misses on a noisy shared box
            if dt > 4.0:
                self.slow.append(dt)
            time.sleep(0.002)

    def stop(self) -> bool:
        self._stop.set()
        self._thread.join(timeout=10)
        self._client.close()
        return not self._thread.is_alive()


class WriteLoad:
    """Writes a fresh tuple every interval on the gRPC write plane;
    acked writes land in the ledger with their token version, typed
    503s are counted (the outage contract), anything else is a
    violation."""

    def __init__(self, port: int, ledger: Ledger):
        import grpc as _grpc

        from keto_tpu.api.client import WriteClient

        self._client = WriteClient(
            _grpc.insecure_channel(f"127.0.0.1:{port}")
        )
        self.ledger = ledger
        self.acked: list[tuple[int, str]] = []
        self.shed_typed = 0
        self.other_errors: list[str] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        import grpc as _grpc

        from keto_tpu.ketoapi import RelationTuple

        n = 0
        while not self._stop.is_set():
            s = f"files:wdoc{n}#owner@writer"
            n += 1
            try:
                tokens = self._client.transact(
                    insert=[RelationTuple.from_string(s)], timeout=5
                )
                if tokens:
                    self.ledger.ack(parse_version(tokens[0]), [s])
                    self.acked.append((parse_version(tokens[0]), s))
            except _grpc.RpcError as e:
                if e.code() == _grpc.StatusCode.UNAVAILABLE:
                    self.shed_typed += 1
                else:
                    self.other_errors.append(
                        f"{e.code()}: {e.details()}"
                    )
            except Exception as e:  # noqa: BLE001
                self.other_errors.append(f"{type(e).__name__}: {e}")
            time.sleep(0.03)

    def stop(self) -> bool:
        self._stop.set()
        self._thread.join(timeout=10)
        self._client.close()
        return not self._thread.is_alive()


class WatchLoad:
    """One gRPC watch stream; counts change/reset/degraded events (the
    client consumes heartbeats silently) and the versions delivered."""

    def __init__(self, port: int):
        import grpc as _grpc

        from keto_tpu.api.client import ReadClient

        self._client = ReadClient(
            _grpc.insecure_channel(f"127.0.0.1:{port}")
        )
        self.events: list[tuple[str, int]] = []
        self._mu = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            for ev in self._client.watch(timeout=600):
                with self._mu:
                    self.events.append(
                        (ev.event_type, parse_version(ev.snaptoken))
                    )
        except Exception:  # noqa: BLE001 — stream ends with the daemon
            pass

    def counts(self) -> dict:
        with self._mu:
            out: dict = {}
            for kind, _v in self.events:
                out[kind] = out.get(kind, 0) + 1
            return out

    def stop(self) -> bool:
        self._client.close()  # closes the channel -> ends the stream
        self._thread.join(timeout=10)
        return not self._thread.is_alive()


# -- helpers -------------------------------------------------------------------


def rest(url, method="GET", body=None, timeout=10):
    req = urllib.request.Request(url, method=method)
    data = None
    if body is not None:
        data = json.dumps(body).encode()
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, data, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def scrape(port: int) -> str:
    _, body, _ = rest(f"http://127.0.0.1:{port}/metrics/prometheus")
    return body.decode()


def grpc_check_error(port, tuple_s, snaptoken):
    import grpc as _grpc

    from keto_tpu.api.client import ReadClient
    from keto_tpu.ketoapi import RelationTuple

    ch = _grpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        ReadClient(ch).check_with_token(
            RelationTuple.from_string(tuple_s), snaptoken=snaptoken,
            timeout=10,
        )
        return None, None
    except _grpc.RpcError as e:
        return e.code().name, e.details()
    finally:
        ch.close()


def wait_for(pred, timeout_s: float, tick=0.02) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(tick)
    return pred()


# -- the outage/recovery drive -------------------------------------------------


def run_cycles(cycles: int, record: dict) -> list[str]:
    from keto_tpu import faults
    from keto_tpu.engine.snaptoken import encode_snaptoken

    violations: list[str] = []
    base = tempfile.mkdtemp(prefix="keto-outage-")
    d = build_daemon(base)
    reg = d.registry
    ledger = Ledger()
    rbase = f"http://127.0.0.1:{d.read_port}"
    wbase = f"http://127.0.0.1:{d.write_port}"
    checkers = [CheckLoad(d.read_port, QUERIES),
                CheckLoad(d.read_grpc_port, QUERIES)]
    writer = WriteLoad(d.write_port, ledger)
    watcher = WatchLoad(d.read_port)
    census_marks: list[int] = []
    per_cycle: list[dict] = []
    try:
        for cycle in range(cycles):
            time.sleep(0.4)  # healthy window under load
            # ---- outage ----
            faults.set_fault("store_outage", error="injected outage")
            opened = wait_for(
                lambda: reg.store_breaker().state == "open", 10
            )
            if not opened:
                violations.append(f"cycle {cycle}: breaker never opened")
                faults.clear()
                continue
            # writes in flight when the fault armed may legitimately
            # ack (they passed the injection point already); once the
            # breaker is open and those have retired, zero writes ack
            time.sleep(0.1)
            pre_acked = len(writer.acked)
            stats: dict = {"cycle": cycle}
            # degraded reads keep answering (covered-token 200s) — give
            # the load a window inside the outage
            time.sleep(0.4)
            # writes shed typed on BOTH write planes, identical shape
            code, body, hdrs = rest(
                f"{wbase}/admin/relation-tuples", "PUT",
                {"namespace": "files", "object": "pdoc", "relation":
                 "owner", "subject_id": "p"},
            )
            parsed = json.loads(body)
            if code != 503 or parsed["error"]["status"] != "store_unavailable":
                violations.append(
                    f"cycle {cycle}: REST write not typed-503: {code} {body!r}"
                )
            if not hdrs.get("Retry-After"):
                violations.append(f"cycle {cycle}: write 503 without Retry-After")
            gcode, gdetails = grpc_write_error(d.write_port)
            if gcode != "UNAVAILABLE" or gdetails != parsed["error"]["message"]:
                violations.append(
                    f"cycle {cycle}: gRPC write shed mismatch: "
                    f"{gcode} {gdetails!r} vs {parsed['error']['message']!r}"
                )
            # tri-plane 503 parity: a token newer than the mirror covers
            covered = reg.check_engine().degraded_covered_version()
            newer = encode_snaptoken(covered + 1, reg.nid)
            code, body, _ = rest(
                f"{rbase}/relation-tuples/check/openapi?namespace=files"
                f"&object=doc0&relation=owner&subject_id=u0&snaptoken={newer}"
            )
            rest_msg = json.loads(body)["error"]["message"] if code == 503 else None
            sync_code, sync_msg = grpc_check_error(d.read_port, QUERIES[0], newer)
            aio_code, aio_msg = grpc_check_error(
                d.read_grpc_port, QUERIES[0], newer
            )
            if not (code == 503 and sync_code == aio_code == "UNAVAILABLE"
                    and rest_msg == sync_msg == aio_msg):
                violations.append(
                    f"cycle {cycle}: tri-plane 503 parity broke: "
                    f"rest={code}/{rest_msg!r} sync={sync_code}/{sync_msg!r} "
                    f"aio={aio_code}/{aio_msg!r}"
                )
            # breaker observable on the metrics plane
            if "keto_tpu_store_breaker_state 1.0" not in scrape(d.metrics_port):
                violations.append(
                    f"cycle {cycle}: open breaker not visible in /metrics"
                )
            if len(writer.acked) != pre_acked:
                violations.append(
                    f"cycle {cycle}: a write was ACKED during the outage"
                )
            # ---- recovery ----
            faults.clear()
            closed = wait_for(
                lambda: reg.store_breaker().state == "closed", 10
            )
            if not closed:
                violations.append(f"cycle {cycle}: breaker never re-closed")
                continue
            # read-your-writes restored: fresh write -> token check True
            import grpc as _grpc

            from keto_tpu.api.client import ReadClient, WriteClient
            from keto_tpu.ketoapi import RelationTuple

            wch = _grpc.insecure_channel(f"127.0.0.1:{d.write_port}")
            rch = _grpc.insecure_channel(f"127.0.0.1:{d.read_port}")
            try:
                s = f"files:rydoc{cycle}#owner@ry"
                tokens = WriteClient(wch).transact(
                    insert=[RelationTuple.from_string(s)], timeout=10
                )
                ledger.ack(parse_version(tokens[0]), [s])
                ok, _tok = ReadClient(rch).check_with_token(
                    RelationTuple.from_string(s), snaptoken=tokens[0],
                    timeout=10,
                )
                if not ok:
                    violations.append(
                        f"cycle {cycle}: read-your-writes broke after recovery"
                    )
            finally:
                wch.close()
                rch.close()
            stats["shed_writes_so_far"] = writer.shed_typed
            stats["degraded_reads_so_far"] = sum(
                c.typed_unavailable for c in checkers
            )
            per_cycle.append(stats)
            census_marks.append(threading.active_count())
    finally:
        faults.clear()
        joined = [c.stop() for c in checkers] + [writer.stop(), watcher.stop()]
        record["load_threads_joined"] = all(joined)
        if not all(joined):
            violations.append("a load thread failed to join (hung thread)")
        d.stop()
        time.sleep(0.5)  # let stopped listeners' threads retire
        post_stop = sorted(
            t.name for t in threading.enumerate()
            if t.name.startswith("keto-") and t.is_alive()
        )
        record["post_stop_keto_threads"] = post_stop
        # the only keto threads allowed to survive stop: the bounded
        # store-op pool (parked on its queue — daemonic by design, see
        # storage/health._OpPool) and daemon-managed background
        # refreshers that are daemon threads parked on events
        n_op = sum(1 for n in post_stop if n.startswith("keto-store-op"))
        if n_op > 4:
            violations.append(
                f"store-op pool grew past its bound: {n_op} threads"
            )
        for name in post_stop:
            if name.startswith(("keto-check-batcher", "keto-mux",
                                "keto-watch-")):
                violations.append(f"serving thread survived stop: {name}")

    # ---- the oracle audit: zero wrong answers at stamped snaptokens ----
    audited = 0
    wrong = 0
    for c in checkers:
        for q, allowed, version in c.answers:
            audited += 1
            if ledger.oracle_allowed(q, version) != allowed:
                wrong += 1
                if len(violations) < 20:
                    violations.append(
                        f"WRONG ANSWER: {q} -> {allowed} at v{version}"
                    )
        for msg in c.other_errors[:5]:
            violations.append(f"non-typed check error: {msg}")
        violations.extend(
            f"slow check ({dt:.1f}s)" for dt in c.slow[:3]
        )
    for msg in writer.other_errors[:5]:
        violations.append(f"non-typed write error: {msg}")
    watch_counts = watcher.counts()
    if watch_counts.get("degraded", 0) < cycles:
        violations.append(
            f"watch degraded markers: {watch_counts.get('degraded', 0)} "
            f"< {cycles} episodes"
        )
    # thread census: bounded across cycles — a wedge-per-cycle bug
    # grows the count every cycle; legitimate lazy spawns (the 4-thread
    # store-op pool, grpc channel pollers) settle within the first
    # couple of cycles, so the baseline is the third mark
    baseline_idx = min(2, len(census_marks) - 1)
    census_clean = (
        len(census_marks) < 2
        or census_marks[-1] <= census_marks[baseline_idx] + 3
    )
    if not census_clean:
        violations.append(f"thread census grew: {census_marks}")
    record.update({
        "cycles": cycles,
        "answers_audited": audited,
        "wrong_answers": wrong,
        "writes_acked": len(writer.acked),
        "writes_shed_typed": writer.shed_typed,
        "checks_typed_unavailable": sum(
            c.typed_unavailable for c in checkers
        ),
        "watch_events": watch_counts,
        "thread_census": census_marks,
        "thread_census_clean": census_clean,
        "per_cycle": per_cycle,
    })
    return violations


def grpc_write_error(port):
    import grpc as _grpc

    from keto_tpu.api.client import WriteClient
    from keto_tpu.ketoapi import RelationTuple

    ch = _grpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        WriteClient(ch).transact(
            insert=[RelationTuple.from_string("files:pdoc#owner@p")],
            timeout=10,
        )
        return None, None
    except _grpc.RpcError as e:
        return e.code().name, e.details()
    finally:
        ch.close()


# -- healthy-path A/B ----------------------------------------------------------


def _measure_arm_pair(dsn: str, windows: int, per_window: int):
    """One on/off daemon pair over `dsn`, alternating measurement
    windows on the served check leg (unique keys, gRPC — the full
    transport -> enforce -> batcher -> engine pipeline); returns
    (median_on_qps, median_off_qps, median of PAIRED window ratios).
    Paired ratios: each window's on-arm divided by its adjacent off-arm
    — box drift on a shared 2-core container hits both halves of a
    pair equally and cancels (the per-call-alternated-medians
    discipline of FLIGHTREC_AB/EXPLAIN_AB, at window grain)."""
    import grpc as _grpc

    from keto_tpu.api.client import ReadClient
    from keto_tpu.ketoapi import RelationTuple

    arms = {}
    for name, health in (("on", True), ("off", False)):
        base = tempfile.mkdtemp(prefix=f"keto-ab-{name}-")
        arms[name] = build_daemon(base, health=health, dsn=dsn)
    clients = {
        name: ReadClient(
            _grpc.insecure_channel(f"127.0.0.1:{d.read_grpc_port}")
        )
        for name, d in arms.items()
    }
    samples: dict[str, list[float]] = {"on": [], "off": []}
    try:
        seq = 0
        for name in arms:  # warm both arms
            clients[name].check(
                RelationTuple.from_string("files:doc0#owner@u0"), timeout=10
            )
        for w in range(windows):
            for name in ("on", "off") if w % 2 == 0 else ("off", "on"):
                c = clients[name]
                t0 = time.perf_counter()
                for _ in range(per_window):
                    seq += 1
                    c.check(
                        RelationTuple.from_string(
                            f"files:doc0#owner@uniq{seq}"
                        ),
                        timeout=10,
                    )
                dt = time.perf_counter() - t0
                samples[name].append(per_window / dt)
    finally:
        for c in clients.values():
            c.close()
        for d in arms.values():
            d.stop()
    ratios = [a / b for a, b in zip(samples["on"], samples["off"])]
    return (
        statistics.median(samples["on"]),
        statistics.median(samples["off"]),
        statistics.median(ratios),
    )


def run_ab(record: dict, windows: int = 30, per_window: int = 60) -> list[str]:
    """The healthy-path A/B, two backend arms:

    - memory (the bench's standard served check leg, the backend every
      committed A/B artifact measures — CACHE_AB_r07 / FLIGHTREC_AB_r08
      / EXPLAIN_AB_r14): store.health on means the inline guard only
      (breaker check + fault probe, ~3 us/op — dict stores cannot hang,
      so no executor). THE 2% BAR APPLIES HERE.
    - sqlite(file): the arm where the op-budget executor is actually
      armed — each served check pays ~2 guarded `version` reads (one at
      snaptoken enforcement, one per engine batch sync), each a
      cross-thread handoff (~20-40 us loaded). On this toy ~5 ms
      request that is measurable (~1-4%); on a real SQL deployment the
      same absolute cost amortizes against genuine query IO. Reported
      with its own looser guard-rail (>= 0.90) so a structural
      regression still fails."""
    mem_on, mem_off, mem_ratio = _measure_arm_pair(
        "memory", windows, per_window
    )
    sq_on, sq_off, sq_ratio = _measure_arm_pair("", windows, per_window)
    record.update({
        "mode": "ab",
        "windows": windows,
        "checks_per_window": per_window,
        "memory": {
            "served_qps_median_health_on": round(mem_on, 1),
            "served_qps_median_health_off": round(mem_off, 1),
            "on_vs_off": round(mem_ratio, 4),
            "bar": "within 2% (>= 0.98) — the standard served check leg",
        },
        "sqlite": {
            "served_qps_median_health_on": round(sq_on, 1),
            "served_qps_median_health_off": round(sq_off, 1),
            "on_vs_off": round(sq_ratio, 4),
            "bar": ">= 0.90 guard-rail (executor-hop arm; see docstring)",
        },
        "on_vs_off": round(mem_ratio, 4),
    })
    out = []
    if mem_ratio < 0.98:
        out.append(
            f"store-health plumbing costs more than 2% on the served "
            f"check leg: on_vs_off={mem_ratio:.4f}"
        )
    if sq_ratio < 0.90:
        out.append(
            f"sqlite executor arm regressed past its guard-rail: "
            f"on_vs_off={sq_ratio:.4f}"
        )
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=4,
                    help="outage/recovery cycles (artifact runs use >= 10)")
    ap.add_argument("--ab", action="store_true",
                    help="run the healthy-path A/B instead of outage cycles")
    ap.add_argument("--ab-windows", type=int, default=30)
    ap.add_argument("--artifact", help="write the full JSON record here")
    args = ap.parse_args()

    record: dict = {
        "tool": "outage_smoke",
        "store": "sqlite(file)",
        "platform": os.environ.get("JAX_PLATFORMS", ""),
    }
    if args.ab:
        violations = run_ab(record, windows=args.ab_windows)
    else:
        violations = run_cycles(args.cycles, record)
    record["violations"] = violations
    record["ok"] = not violations
    line = json.dumps(record)
    print(line)
    if args.artifact:
        with open(args.artifact, "w") as f:
            f.write(line + "\n")
    return 0 if not violations else 1


if __name__ == "__main__":
    sys.exit(main())
