#!/usr/bin/env python
"""Closure-under-churn correctness smoke (CI-wired, CPU-runnable).

The Leopard index's acceptance property is behavioral, not structural:
under interleaved writes the index lags, marks dirty, re-powers — and
through ALL of it every Check() answer must equal the exact host
oracle's. This smoke drives that loop deterministically:

  scenario_churn     — single-threaded interleaving of writes, closure
                       maintenance steps, and differential check batches
                       against the host oracle: ZERO wrong answers, and
                       the fallback->catch-up->hit transitions must be
                       OBSERVABLE in the engine's closure counters.
  scenario_held_tail — the maintainer is HELD (the replica_smoke forced-
                       lag trick): writes land, the index cannot catch
                       up beyond the inline budget, answers stay
                       oracle-correct the whole time; releasing the
                       maintainer restores hits.
  scenario_stores    — the churn loop repeated on memory, sqlite and
                       columnar stores (the closure builder's three
                       ingest shapes).

`--powering device` runs the same loop with `closure.powering =
"device"` — every (re)build routed through the bit-packed GraphBLAS
kernel (engine/closure_power.py) — and additionally requires the
builds to be OBSERVABLY device-powered: `device_builds > 0` and zero
`device_fallbacks` per store kind, so a silently host-falling-back
kernel cannot pass.

Run: python tools/closure_correctness.py  (exit 0 = all invariants held)
     python tools/closure_correctness.py --powering device
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import random  # noqa: E402

from keto_tpu.config import Config  # noqa: E402
from keto_tpu.engine.definitions import Membership  # noqa: E402
from keto_tpu.engine.reference import ReferenceEngine  # noqa: E402
from keto_tpu.engine.tpu_engine import TPUCheckEngine  # noqa: E402
from keto_tpu.ketoapi import RelationTuple  # noqa: E402
from keto_tpu.namespace import Namespace  # noqa: E402
from keto_tpu.namespace.ast import (  # noqa: E402
    ComputedSubjectSet,
    Relation,
    SubjectSetRewrite,
    TupleToSubjectSet,
)

DEPTH = 8
N_CHAINS = 12
N_USERS = 16


def deep_namespaces():
    return [Namespace(name="deep", relations=[
        Relation(name="owner"),
        Relation(name="parent"),
        Relation(name="viewer", subject_set_rewrite=SubjectSetRewrite(
            children=[
                ComputedSubjectSet(relation="owner"),
                TupleToSubjectSet(
                    relation="parent",
                    computed_subject_set_relation="viewer",
                ),
            ]
        )),
    ])]


def seed_tuples(rng):
    tuples = []
    for c in range(N_CHAINS):
        for i in range(DEPTH):
            tuples.append(RelationTuple.from_string(
                f"deep:c{c}f{i}#parent@(deep:c{c}f{i + 1}#...)"
            ))
        tuples.append(RelationTuple.from_string(
            f"deep:c{c}f{DEPTH}#owner@u{rng.randrange(N_USERS)}"
        ))
    return tuples


def make_store(kind: str, tmpdir: str):
    if kind == "memory":
        from keto_tpu.storage import MemoryManager

        return MemoryManager()
    if kind == "sqlite":
        from keto_tpu.storage.sqlite import SQLPersister

        return SQLPersister(f"sqlite://{tmpdir}/closure_smoke_{os.getpid()}.db")
    if kind == "columnar":
        from keto_tpu.storage.columnar import ColumnarStore

        return ColumnarStore()
    raise ValueError(kind)


def run_churn(store_kind: str, tmpdir: str, rounds: int = 30,
              hold_tail: bool = False, powering: str = "host") -> dict:
    rng = random.Random(42)
    cfg = Config({
        "limit": {"max_read_depth": DEPTH + 4},
        "closure": {
            "enabled": True,
            "lag_budget_versions": 0 if hold_tail else 64,
            "powering": powering,
        },
    })
    cfg.set_namespaces(deep_namespaces())
    manager = make_store(store_kind, tmpdir)
    manager.write_relation_tuples(seed_tuples(rng))
    engine = TPUCheckEngine(manager, cfg, frontier_cap=4096)
    oracle = ReferenceEngine(manager, cfg)
    assert engine.closure_ensure_built(), "initial powering must succeed"

    wrong = 0
    checked = 0
    transitions = {"hit": 0, "fallback": 0, "recovered": 0}
    was_falling_back = False
    next_user = [N_USERS]
    for r in range(rounds):
        # one committed write per round: new member at a random chain
        # tail, or delete one previously added
        c = rng.randrange(N_CHAINS)
        if rng.random() < 0.7:
            u = f"w{next_user[0]}"
            next_user[0] += 1
            manager.write_relation_tuples([RelationTuple.from_string(
                f"deep:c{c}f{rng.randrange(DEPTH + 1)}#owner@{u}"
            )])
        else:
            manager.delete_relation_tuples([RelationTuple.from_string(
                f"deep:c{c}f{DEPTH}#owner@u{rng.randrange(N_USERS)}"
            )])
        # maintenance runs only when the tail is NOT held: held = the
        # forced-lag regime, the index must refuse rather than answer
        if not hold_tail and r % 3 == 2:
            engine.closure_ensure_built()

        hits0 = engine.stats.get("closure_hits", 0)
        fb0 = sum(engine.stats.get("closure_fallback", {}).values())
        queries = []
        for _ in range(16):
            qc = rng.randrange(N_CHAINS)
            qf = rng.randrange(DEPTH)
            sub = (
                f"u{rng.randrange(N_USERS)}"
                if rng.random() < 0.5
                else f"w{rng.randrange(max(next_user[0] - N_USERS, 1)) + N_USERS}"
            )
            queries.append(RelationTuple.from_string(
                f"deep:c{qc}f{qf}#viewer@{sub}"
            ))
        results = engine.check_batch(queries)
        for q, res in zip(queries, results):
            want = oracle.check_relation_tuple(q)
            checked += 1
            if res.membership != want.membership:
                wrong += 1
        hit_d = engine.stats.get("closure_hits", 0) - hits0
        fb_d = sum(engine.stats.get("closure_fallback", {}).values()) - fb0
        if fb_d:
            transitions["fallback"] += 1
            was_falling_back = True
        if hit_d and not fb_d:
            transitions["hit"] += 1
            if was_falling_back:
                transitions["recovered"] += 1
                was_falling_back = False
    return {
        "store": store_kind,
        "hold_tail": hold_tail,
        "rounds": rounds,
        "checked": checked,
        "wrong": wrong,
        "closure_hits": engine.stats.get("closure_hits", 0),
        "closure_fallback": dict(engine.stats.get("closure_fallback", {})),
        "transitions": transitions,
        "index": engine.closure_index().describe(),
    }


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--powering", choices=("host", "device"), default="host",
        help="closure builder under test: 'host' (numpy powering) or "
             "'device' (engine/closure_power.py GraphBLAS kernel — the "
             "same churn loop, plus the requirement that builds "
             "OBSERVABLY ran through the kernel with zero fallbacks "
             "to host)",
    )
    args = ap.parse_args()

    failures = []
    with tempfile.TemporaryDirectory() as tmpdir:
        for kind in ("memory", "sqlite", "columnar"):
            rec = run_churn(kind, tmpdir, powering=args.powering)
            print(f"[churn/{kind}] {rec}")
            if rec["wrong"]:
                failures.append(f"{kind}: {rec['wrong']} wrong answers")
            if rec["closure_hits"] == 0:
                failures.append(f"{kind}: closure never hit")
            if not sum(rec["closure_fallback"].values()):
                failures.append(
                    f"{kind}: churn produced zero observable fallbacks"
                )
            if rec["transitions"]["recovered"] == 0:
                failures.append(
                    f"{kind}: no fallback->catch-up->hit transition observed"
                )
            if args.powering == "device":
                # the kernel must have actually powered the index —
                # silent host fallbacks would pass every answer check
                # while testing nothing
                if rec["index"].get("device_builds", 0) == 0:
                    failures.append(
                        f"{kind}: device powering never built the index"
                    )
                if rec["index"].get("device_fallbacks", 0):
                    failures.append(
                        f"{kind}: {rec['index']['device_fallbacks']} "
                        "device powerings fell back to host"
                    )

        held = run_churn("memory", tmpdir, hold_tail=True,
                         powering=args.powering)
        print(f"[held-tail] {held}")
        if held["wrong"]:
            failures.append(f"held-tail: {held['wrong']} wrong answers")
        lagged = sum(
            n for c, n in held["closure_fallback"].items()
            if c in ("lag", "dirty", "stale_snapshot")
        )
        if lagged == 0:
            failures.append(
                "held-tail: a held maintainer produced no lag/dirty fallbacks"
            )

    if failures:
        print("FAIL:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"OK[{args.powering}]: zero wrong answers under churn; "
          "fallback/catch-up/hit transitions observable; held tail "
          "degraded safely")
    return 0


if __name__ == "__main__":
    sys.exit(main())
