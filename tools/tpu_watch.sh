#!/bin/bash
# Watch for axon TPU recovery; on the first healthy probe, capture the
# full round-3 artifact session (tools/tpu_session.py) immediately —
# healthy windows between tunnel wedges can be short.
cd "$(dirname "$0")/.." || exit 1
for i in $(seq 1 "${TPU_WATCH_ATTEMPTS:-200}"); do
  ts=$(date +%H:%M:%S)
  out=$(timeout 90 python -c "import jax, jax.numpy as jnp; x=jnp.ones((128,128)); (x@x).block_until_ready(); print('PROBE_OK', jax.devices()[0])" 2>/dev/null)
  if echo "$out" | grep -q PROBE_OK; then
    echo "$ts RECOVERED: $out" >> "${TPU_WATCH_LOG:-/tmp/tpu_probe.log}"
    python tools/tpu_session.py >> "${TPU_WATCH_LOG:-/tmp/tpu_probe.log}" 2>&1
    exit $?
  fi
  echo "$ts still wedged" >> "${TPU_WATCH_LOG:-/tmp/tpu_probe.log}"
  sleep "${TPU_WATCH_INTERVAL:-60}"
done
echo "watch exhausted" >> "${TPU_WATCH_LOG:-/tmp/tpu_probe.log}"
exit 1
