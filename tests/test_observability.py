"""Tracing instrumentation + config schema validation (VERDICT round-1
item 8): spans visible in a test exporter; bad config rejected at load
with a pointer to the offending key."""

import json
import urllib.request

import pytest

from keto_tpu.config import Config, ConfigError
from keto_tpu.api.daemon import Daemon
from keto_tpu.ketoapi import RelationTuple
from keto_tpu.namespace import Namespace
from keto_tpu.registry import Registry


class TestConfigSchema:
    def test_unknown_top_level_key_rejected(self):
        with pytest.raises(ConfigError) as e:
            Config({"dns": "memory"})  # typo of dsn
        assert "dns" in str(e.value)

    def test_bad_nested_value_names_the_key(self):
        with pytest.raises(ConfigError) as e:
            Config({"limit": {"max_read_depth": "five"}})
        assert "limit.max_read_depth" in str(e.value)

    def test_bad_engine_enum(self):
        with pytest.raises(ConfigError):
            Config({"check": {"engine": "gpu"}})

    def test_set_validates_and_rolls_back(self):
        cfg = Config({"limit": {"max_read_depth": 5}})
        with pytest.raises(ConfigError):
            cfg.set("limit.max_read_depth", -3)
        assert cfg.max_read_depth() == 5  # untouched after rejection

    def test_immutable_keys_still_enforced(self):
        cfg = Config({"dsn": "memory"})
        with pytest.raises(ConfigError):
            cfg.set("dsn", "columnar")

    def test_valid_config_passes(self):
        Config({
            "dsn": "memory",
            "check": {"engine": "tpu", "frontier_cap": 4096},
            "serve": {"read": {"host": "127.0.0.1", "port": 0}},
            "tracing": {"enabled": True, "provider": "memory"},
            "tenancy": {"header": "x-keto-network"},
        })


class TestTracing:
    def test_spans_cover_store_engine_and_rpc(self):
        cfg = Config({
            "dsn": "memory",
            "check": {"engine": "tpu"},
            "tracing": {"enabled": True, "provider": "memory"},
            "serve": {
                "read": {"host": "127.0.0.1", "port": 0},
                "write": {"host": "127.0.0.1", "port": 0},
                "metrics": {"host": "127.0.0.1", "port": 0},
            },
        })
        cfg.set_namespaces([Namespace(name="files")])
        reg = Registry(cfg)
        reg.relation_tuple_manager().write_relation_tuples(
            [RelationTuple.from_string("files:doc#owner@alice")]
        )
        d = Daemon(reg)
        d.start()
        try:
            u = (
                f"http://127.0.0.1:{d.read_port}/relation-tuples/check/openapi"
                "?namespace=files&object=doc&relation=owner&subject_id=alice"
            )
            assert json.load(urllib.request.urlopen(u))["allowed"] is True
        finally:
            d.stop()
        names = reg.tracer().span_names()
        # store op, snapshot build, kernel launch, result resolution, and
        # the HTTP request span must all be present
        assert "persistence.write_relation_tuples" in names
        assert "engine.snapshot_build" in names
        assert "engine.kernel_launch" in names
        assert "engine.resolve_batch" in names
        assert any(n.startswith("http.") for n in names)

    def test_tracing_disabled_is_noop(self):
        cfg = Config({"dsn": "memory"})
        cfg.set_namespaces([Namespace(name="files")])
        reg = Registry(cfg)
        t = reg.tracer()
        with t.span("anything") as s:
            s.set_attribute("k", "v")
        assert not hasattr(t, "spans")
