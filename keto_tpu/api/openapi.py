"""Machine-readable REST API spec, generated from the route table.

The reference ships a generated Swagger document and serves API docs
(/root/reference/spec/swagger.json, /root/reference/doc_swagger.go:1,
swagger param shims internal/relationtuple/swagger_definitions.go); here
the OpenAPI 3.0 document is BUILT from the same route constants
rest_server.py dispatches on, so the spec cannot drift from the router.
Served at GET /.well-known/openapi.json on the read and write routers.
"""

from __future__ import annotations

from .rest_server import (
    ALIVE_PATH,
    CHECK_BATCH_ROUTE,
    CHECK_OPENAPI_ROUTE,
    CHECK_ROUTE_BASE,
    EXPAND_ROUTE,
    FILTER_ROUTE,
    LIST_OBJECTS_ROUTE,
    LIST_SUBJECTS_ROUTE,
    READ_ROUTE_BASE,
    READY_PATH,
    ROUTE_KINDS,
    VERSION_PATH,
    WATCH_ROUTE,
    WRITE_ROUTE_BASE,
)


_SUBJECT_QUERY_PARAMS = [
    {"name": "namespace", "in": "query", "schema": {"type": "string"}},
    {"name": "object", "in": "query", "schema": {"type": "string"}},
    {"name": "relation", "in": "query", "schema": {"type": "string"}},
    {"name": "subject_id", "in": "query", "schema": {"type": "string"}},
    {
        "name": "subject_set.namespace",
        "in": "query",
        "schema": {"type": "string"},
    },
    {"name": "subject_set.object", "in": "query", "schema": {"type": "string"}},
    {
        "name": "subject_set.relation",
        "in": "query",
        "schema": {"type": "string"},
    },
]

_MAX_DEPTH_PARAM = {
    "name": "max-depth",
    "in": "query",
    "schema": {"type": "integer"},
    "description": "Maximum traversal depth (0 = server default)",
}


def _schemas() -> dict:
    subject_set = {
        "type": "object",
        "required": ["namespace", "object", "relation"],
        "properties": {
            "namespace": {"type": "string"},
            "object": {"type": "string"},
            "relation": {"type": "string"},
        },
    }
    relation_tuple = {
        "type": "object",
        "required": ["namespace", "object", "relation"],
        "properties": {
            "namespace": {"type": "string"},
            "object": {"type": "string"},
            "relation": {"type": "string"},
            "subject_id": {"type": "string"},
            "subject_set": {"$ref": "#/components/schemas/subjectSet"},
        },
    }
    return {
        "subjectSet": subject_set,
        "relationTuple": relation_tuple,
        "checkResponse": {
            "type": "object",
            "required": ["allowed"],
            "properties": {
                "allowed": {"type": "boolean"},
                "decision_trace": {
                    "$ref": "#/components/schemas/decisionTrace"
                },
            },
        },
        "decisionTrace": {
            "type": "object",
            "description": "why a Check answered what it did (keto_tpu "
                           "§5m explain plane; present only when the "
                           "request set explain=true): the answering "
                           "tier + cause, a host-re-walked witness path "
                           "for ALLOW (differential-checked against the "
                           "authoritative device verdict), an "
                           "exhaustion summary for DENY, per-stage ms, "
                           "and flight-recorder launch ids",
            "properties": {
                "allowed": {"type": "boolean"},
                "tier": {
                    "type": "string",
                    "description": "which tier answered: closure "
                                   "(Leopard one-step probe) | device "
                                   "(BFS kernel) | host (exact oracle "
                                   "replay) | vocab (name outside the "
                                   "configured vocabulary)",
                },
                "cause": {"type": ["string", "null"]},
                "closure_fallback": {"type": ["string", "null"]},
                "version": {"type": "integer"},
                "enforce_version": {"type": "integer"},
                "snaptoken": {"type": "string"},
                "max_depth": {"type": ["integer", "null"]},
                "witness": {
                    "type": "array",
                    "description": "the edge/rewrite chain proving "
                                   "ALLOW, query -> direct tuple, one "
                                   "hop per traversal rule with the "
                                   "tuple it rode and the rest-depth",
                    "items": {"type": "object"},
                },
                "exhaustion": {
                    "type": ["object", "null"],
                    "description": "DENY only: depth guards hit, nodes "
                                   "visited, tuples scanned, AND/NOT "
                                   "islands consulted",
                },
                "witness_verdict": {"type": "boolean"},
                "witness_consistent": {"type": "boolean"},
                "witness_racy": {"type": "boolean"},
                "cache_bypassed": {"type": "boolean"},
                "stages_ms": {"type": "object"},
                "launch_ids": {
                    "type": "array", "items": {"type": "integer"},
                },
            },
        },
        "batchCheckRequest": {
            "type": "object",
            "required": ["tuples"],
            "properties": {
                "tuples": {
                    "type": "array",
                    "items": {"$ref": "#/components/schemas/relationTuple"},
                },
                "max_depth": {"type": "integer"},
                "snaptoken": {"type": "string"},
            },
        },
        "batchCheckResponse": {
            "type": "object",
            "required": ["results"],
            "properties": {
                "snaptoken": {"type": "string"},
                "results": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "required": ["allowed"],
                        "properties": {
                            "allowed": {"type": "boolean"},
                            "error": {"type": "string"},
                        },
                    },
                },
            },
        },
        "filterRequest": {
            "type": "object",
            "required": ["namespace", "relation", "objects"],
            "properties": {
                "namespace": {"type": "string"},
                "relation": {"type": "string"},
                "subject_id": {"type": "string"},
                "subject_set": {
                    "$ref": "#/components/schemas/subjectSet"
                },
                "objects": {
                    "type": "array",
                    "items": {"type": "string"},
                    "description": "candidate object names — the whole "
                                   "column rides one device evaluation "
                                   "(bounded by filter.max_objects)",
                },
                "max_depth": {"type": "integer"},
                "snaptoken": {"type": "string"},
            },
        },
        "filterResponse": {
            "type": "object",
            "required": ["allowed_objects"],
            "properties": {
                "allowed_objects": {
                    "type": "array",
                    "items": {"type": "string"},
                    "description": "candidates the subject can see, in "
                                   "request order",
                },
                "snaptoken": {"type": "string"},
            },
        },
        "listObjectsResponse": {
            "type": "object",
            "required": ["objects"],
            "properties": {
                "objects": {
                    "type": "array",
                    "items": {"type": "string"},
                    "description": "sorted object names the subject "
                                   "reaches (deterministic pagination)",
                },
                "next_page_token": {"type": "string"},
            },
        },
        "listSubjectsResponse": {
            "type": "object",
            "required": ["subject_ids"],
            "properties": {
                "subject_ids": {
                    "type": "array",
                    "items": {"type": "string"},
                    "description": "sorted plain subject ids that reach "
                                   "the object",
                },
                "next_page_token": {"type": "string"},
            },
        },
        "getResponse": {
            "type": "object",
            "required": ["relation_tuples"],
            "properties": {
                "relation_tuples": {
                    "type": "array",
                    "items": {"$ref": "#/components/schemas/relationTuple"},
                },
                "next_page_token": {"type": "string"},
            },
        },
        "expandTree": {
            "type": "object",
            "required": ["type"],
            "properties": {
                "type": {
                    "type": "string",
                    "enum": ["union", "exclusion", "intersection",
                             "leaf", "unspecified"],
                },
                "tuple": {"$ref": "#/components/schemas/relationTuple"},
                "children": {
                    "type": "array",
                    "items": {"$ref": "#/components/schemas/expandTree"},
                },
            },
        },
        "patchDelta": {
            "type": "object",
            "required": ["action", "relation_tuple"],
            "properties": {
                "action": {"type": "string", "enum": ["insert", "delete"]},
                "relation_tuple": {
                    "$ref": "#/components/schemas/relationTuple"
                },
            },
        },
        "version": {
            "type": "object",
            "required": ["version"],
            "properties": {"version": {"type": "string"}},
        },
        "healthStatus": {
            "type": "object",
            "properties": {"status": {"type": "string"}},
        },
        "watchEvent": {
            "type": "object",
            "required": ["event_type", "snaptoken", "changes"],
            "properties": {
                "event_type": {
                    "type": "string",
                    "enum": ["change", "reset"],
                    "description": "change = one committed store version; "
                                   "reset = unrecoverable gap (overflow, "
                                   "trimmed changelog) — re-read state and "
                                   "resume from the carried snaptoken",
                },
                "snaptoken": {
                    "type": "string",
                    "description": "the committed version's token — the "
                                   "resumable cursor",
                },
                "changes": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "required": ["action", "relation_tuple"],
                        "properties": {
                            "action": {
                                "type": "string",
                                "enum": ["insert", "delete"],
                            },
                            "relation_tuple": {
                                "$ref": "#/components/schemas/relationTuple"
                            },
                        },
                    },
                },
            },
        },
        "errorGeneric": {
            "type": "object",
            "required": ["error"],
            "properties": {
                "error": {
                    "type": "object",
                    "properties": {
                        "code": {"type": "integer"},
                        "status": {"type": "string"},
                        "message": {"type": "string"},
                    },
                },
            },
        },
    }


def _json_response(desc: str, ref: str | None = None) -> dict:
    out: dict = {"description": desc}
    if ref is not None:
        out["content"] = {
            "application/json": {
                "schema": {"$ref": f"#/components/schemas/{ref}"}
            }
        }
    return out


def build_spec(version: str = "", kind: str | None = None) -> dict:
    """The OpenAPI 3.0 document for the REST surface. Route strings AND
    route→port ownership come from rest_server (ROUTE_KINDS), so `kind`
    ("read" | "write" | None) filters to the paths THAT router answers —
    each port's served spec must not advertise routes the port 404s."""
    snaptoken_param = {
        "name": "snaptoken", "in": "query",
        "schema": {"type": "string"},
        "description": "pin the read to at least this snapshot "
                       "(keto_tpu extension; from a write response)",
    }
    snaptoken_header = {
        "X-Keto-Snaptoken": {
            "schema": {"type": "string"},
            "description": "token of the snapshot this response was "
                           "evaluated against (keto_tpu extension)",
        }
    }
    explain_param = {
        "name": "explain", "in": "query",
        "schema": {"type": "boolean"},
        "description": "return a DecisionTrace beside the verdict "
                       "(keto_tpu §5m extension): answering tier, "
                       "witness path / exhaustion summary, stage ms, "
                       "launch ids. Bypasses the check cache; "
                       "rate-bounded by explain.max_per_s (429 over "
                       "the bound). POST also accepts an `explain` "
                       "body field",
    }
    check_op = {
        "parameters": _SUBJECT_QUERY_PARAMS + [_MAX_DEPTH_PARAM,
                                               snaptoken_param,
                                               explain_param],
        "responses": {
            "200": {
                **_json_response("membership verdict", "checkResponse"),
                "headers": snaptoken_header,
            },
            "400": _json_response("malformed input", "errorGeneric"),
            "409": _json_response(
                "snaptoken demands a newer snapshot", "errorGeneric"
            ),
        },
    }
    check_bare = {
        **check_op,
        "responses": {
            **check_op["responses"],
            "403": _json_response("denied (bare route mirrors the verdict "
                                  "as the status code)", "checkResponse"),
        },
    }
    # POST check takes the subject tuple from the JSON body ONLY (the
    # handler ignores subject query params on POST, like the reference's
    # postCheck vs getCheck split, rest_server._Handler._check)
    # — so the POST operations carry a required body and just max-depth
    check_body = {
        "required": True,
        "content": {"application/json": {"schema": {
            "$ref": "#/components/schemas/relationTuple"
        }}},
    }
    check_op_post = {
        **check_op, "requestBody": check_body,
        "parameters": [_MAX_DEPTH_PARAM, snaptoken_param, explain_param],
    }
    check_bare_post = {
        **check_bare, "requestBody": check_body,
        "parameters": [_MAX_DEPTH_PARAM, snaptoken_param, explain_param],
    }
    paths = {
        READ_ROUTE_BASE: {
            "get": {
                "summary": "List relation tuples matching a query",
                "parameters": _SUBJECT_QUERY_PARAMS + [
                    {"name": "page_token", "in": "query",
                     "schema": {"type": "string"}},
                    {"name": "page_size", "in": "query",
                     "schema": {"type": "integer"}},
                ],
                "responses": {
                    "200": _json_response("matching tuples", "getResponse"),
                    "400": _json_response("malformed input", "errorGeneric"),
                    "404": _json_response("unknown namespace", "errorGeneric"),
                },
            }
        },
        CHECK_ROUTE_BASE: {"get": check_bare, "post": check_bare_post},
        CHECK_OPENAPI_ROUTE: {"get": check_op, "post": check_op_post},
        CHECK_BATCH_ROUTE: {
            "post": {
                "summary": "Check a batch of relation tuples in one "
                           "round-trip (keto_tpu extension)",
                "parameters": [_MAX_DEPTH_PARAM],
                "requestBody": {
                    "required": True,
                    "content": {"application/json": {"schema": {
                        "$ref": "#/components/schemas/batchCheckRequest"
                    }}},
                },
                "responses": {
                    "200": _json_response(
                        "per-tuple verdicts in request order",
                        "batchCheckResponse",
                    ),
                    "400": _json_response("malformed input", "errorGeneric"),
                },
            }
        },
        EXPAND_ROUTE: {
            "get": {
                "summary": "Expand a subject set into its membership tree",
                "parameters": [
                    {"name": "namespace", "in": "query", "required": True,
                     "schema": {"type": "string"}},
                    {"name": "object", "in": "query", "required": True,
                     "schema": {"type": "string"}},
                    {"name": "relation", "in": "query", "required": True,
                     "schema": {"type": "string"}},
                    _MAX_DEPTH_PARAM,
                ],
                "responses": {
                    "200": _json_response("expansion tree", "expandTree"),
                    "400": _json_response("malformed input", "errorGeneric"),
                    "404": _json_response("no such subject set",
                                          "errorGeneric"),
                },
            }
        },
        FILTER_ROUTE: {
            "post": {
                "summary": "Filter a candidate object list down to what "
                           "the subject can see (keto_tpu bulk-ACL-"
                           "filter extension — one request, many "
                           "objects, one device ride)",
                "requestBody": {
                    "required": True,
                    "content": {"application/json": {"schema": {
                        "$ref": "#/components/schemas/filterRequest"
                    }}},
                },
                "responses": {
                    "200": _json_response(
                        "candidates the subject can see, in request "
                        "order",
                        "filterResponse",
                    ),
                    "400": _json_response(
                        "malformed input or candidate list over "
                        "filter.max_objects",
                        "errorGeneric",
                    ),
                    "404": _json_response("unknown namespace", "errorGeneric"),
                    "409": _json_response(
                        "snaptoken demands a newer snapshot", "errorGeneric"
                    ),
                    "429": _json_response(
                        "server overloaded or draining", "errorGeneric"
                    ),
                    "504": _json_response(
                        "deadline expired mid-evaluation", "errorGeneric"
                    ),
                },
            }
        },
        LIST_OBJECTS_ROUTE: {
            "get": {
                "summary": "List the objects a subject reaches via a "
                           "relation (keto_tpu reverse-reachability "
                           "extension)",
                "parameters": _SUBJECT_QUERY_PARAMS + [
                    _MAX_DEPTH_PARAM, snaptoken_param,
                    {"name": "page_size", "in": "query",
                     "schema": {"type": "integer"}},
                    {"name": "page_token", "in": "query",
                     "schema": {"type": "string"}},
                ],
                "responses": {
                    "200": {
                        **_json_response(
                            "objects the subject reaches",
                            "listObjectsResponse",
                        ),
                        "headers": snaptoken_header,
                    },
                    "400": _json_response("malformed input", "errorGeneric"),
                    "404": _json_response("unknown namespace", "errorGeneric"),
                    "409": _json_response(
                        "snaptoken demands a newer snapshot", "errorGeneric"
                    ),
                },
            }
        },
        LIST_SUBJECTS_ROUTE: {
            "get": {
                "summary": "List the subject ids that reach an object "
                           "(keto_tpu reverse-reachability extension)",
                "parameters": [
                    {"name": "namespace", "in": "query", "required": True,
                     "schema": {"type": "string"}},
                    {"name": "object", "in": "query", "required": True,
                     "schema": {"type": "string"}},
                    {"name": "relation", "in": "query", "required": True,
                     "schema": {"type": "string"}},
                    _MAX_DEPTH_PARAM, snaptoken_param,
                    {"name": "page_size", "in": "query",
                     "schema": {"type": "integer"}},
                    {"name": "page_token", "in": "query",
                     "schema": {"type": "string"}},
                ],
                "responses": {
                    "200": {
                        **_json_response(
                            "subject ids that reach the object",
                            "listSubjectsResponse",
                        ),
                        "headers": snaptoken_header,
                    },
                    "400": _json_response("malformed input", "errorGeneric"),
                    "404": _json_response("unknown namespace", "errorGeneric"),
                    "409": _json_response(
                        "snaptoken demands a newer snapshot", "errorGeneric"
                    ),
                },
            }
        },
        WATCH_ROUTE: {
            "get": {
                "summary": "Stream the tuple changelog as Server-Sent "
                           "Events (keto_tpu watch extension; Zanzibar's "
                           "Watch API)",
                "parameters": [
                    snaptoken_param,
                    {"name": "namespace", "in": "query",
                     "schema": {"type": "string"},
                     "description": "only stream changes in this "
                                    "namespace (reset events always "
                                    "pass the filter)"},
                    {"name": "max_events", "in": "query",
                     "schema": {"type": "integer"},
                     "description": "close the stream after N events "
                                    "(scripting/testing aid)"},
                ],
                "responses": {
                    "200": {
                        "description": "SSE stream; each message is one "
                                       "committed store version (event: "
                                       "change|reset, data: watchEvent)",
                        "content": {
                            "text/event-stream": {
                                "schema": {
                                    "$ref": "#/components/schemas/watchEvent"
                                }
                            }
                        },
                    },
                    "400": _json_response("malformed snaptoken",
                                          "errorGeneric"),
                    "404": _json_response("unknown namespace", "errorGeneric"),
                    "409": _json_response(
                        "snaptoken demands a newer snapshot", "errorGeneric"
                    ),
                },
            }
        },
        WRITE_ROUTE_BASE: {
            "put": {
                "summary": "Create one relation tuple",
                "requestBody": {
                    "required": True,
                    "content": {"application/json": {"schema": {
                        "$ref": "#/components/schemas/relationTuple"
                    }}},
                },
                "responses": {
                    "201": _json_response("created", "relationTuple"),
                    "400": _json_response("malformed input", "errorGeneric"),
                    "404": _json_response("unknown namespace", "errorGeneric"),
                },
            },
            "delete": {
                "summary": "Delete all relation tuples matching the query",
                "parameters": _SUBJECT_QUERY_PARAMS,
                "responses": {
                    "204": {"description": "deleted"},
                    "400": _json_response("malformed input", "errorGeneric"),
                    "404": _json_response("unknown namespace", "errorGeneric"),
                },
            },
            "patch": {
                "summary": "Apply insert/delete deltas transactionally",
                "requestBody": {
                    "required": True,
                    "content": {"application/json": {"schema": {
                        "type": "array",
                        "items": {"$ref": "#/components/schemas/patchDelta"},
                    }}},
                },
                "responses": {
                    "204": {"description": "applied"},
                    "400": _json_response("malformed input", "errorGeneric"),
                    "404": _json_response("unknown namespace", "errorGeneric"),
                },
            },
        },
        ALIVE_PATH: {"get": {"responses": {
            "200": _json_response("process is alive", "healthStatus")}}},
        READY_PATH: {"get": {"responses": {
            "200": _json_response("ready to serve", "healthStatus"),
            "503": _json_response("not ready", "errorGeneric")}}},
        VERSION_PATH: {"get": {"responses": {
            "200": _json_response("build version", "version")}}},
    }
    op_ids = {
        (READ_ROUTE_BASE, "get"): "listRelationTuples",
        (CHECK_ROUTE_BASE, "get"): "getCheckMirrorStatus",
        (CHECK_ROUTE_BASE, "post"): "postCheckMirrorStatus",
        (CHECK_OPENAPI_ROUTE, "get"): "getCheck",
        (CHECK_OPENAPI_ROUTE, "post"): "postCheck",
        (CHECK_BATCH_ROUTE, "post"): "postBatchCheck",
        (EXPAND_ROUTE, "get"): "getExpand",
        (FILTER_ROUTE, "post"): "postFilter",
        (LIST_OBJECTS_ROUTE, "get"): "getListObjects",
        (LIST_SUBJECTS_ROUTE, "get"): "getListSubjects",
        (WATCH_ROUTE, "get"): "getWatch",
        (WRITE_ROUTE_BASE, "put"): "createRelationTuple",
        (WRITE_ROUTE_BASE, "delete"): "deleteRelationTuples",
        (WRITE_ROUTE_BASE, "patch"): "patchRelationTuples",
        (ALIVE_PATH, "get"): "isAlive",
        (READY_PATH, "get"): "isReady",
        (VERSION_PATH, "get"): "getVersion",
    }
    # the per-method dicts are shared between routes (check_op/check_bare),
    # so operationIds go on per-use copies, keyed like the reference's
    # swagger operationIds (httpclient-next method names derive from these)
    paths = {
        p: {m: {**op, "operationId": op_ids[(p, m)]} for m, op in ops.items()}
        for p, ops in paths.items()
    }
    if kind in ("read", "write"):
        # ROUTE_KINDS[p] (not .get): a path missing from the ownership
        # table must raise here — failing open to "shared" would put the
        # route in BOTH ports' specs, the drift this filter exists to stop
        paths = {
            p: ops
            for p, ops in paths.items()
            if ROUTE_KINDS[p] in (kind, "shared")
        }
    return {
        "openapi": "3.0.3",
        "info": {
            "title": "keto_tpu read/write API",
            "version": version or "dev",
            "description": (
                "Wire-compatible REST surface of the keto_tpu daemon "
                "(reference parity: spec/swagger.json)"
            ),
        },
        "paths": paths,
        "components": {"schemas": _schemas()},
    }
