"""Embedding/extension hooks: per-request tenancy (Contextualizer).

Parity with the reference's ketoctx package: embedders derive the
network id per request instead of pinning one at startup
(/root/reference/ketoctx/contextualizer.go:12-19 `Contextualizer.
Network(ctx, fallback)`; the SQL persister resolves it per query,
internal/persistence/sql/persister.go:93-95).

Here the request context is the transport metadata mapping (HTTP
headers / gRPC invocation metadata, case-insensitive keys). The stores
are already nid-scoped (every Manager method takes nid=) and the TPU
engine keeps one device mirror per network, so the registry only needs
the hook plus a per-nid engine cache (registry.check_engine(nid)).

Enable via config:

    tenancy:
      header: x-keto-network   # derive nid from this header/metadata key

or programmatically: Registry(cfg, contextualizer=MyContextualizer()).
"""

from __future__ import annotations

from typing import Mapping, Optional, Protocol


class Contextualizer(Protocol):
    def network(self, metadata: Mapping[str, str], fallback: str) -> str:
        """The network id for one request; `fallback` is the registry's
        configured default."""
        ...


class DefaultContextualizer:
    """Single-tenant: always the configured network (the reference's
    defaultContextualizer)."""

    def network(self, metadata: Mapping[str, str], fallback: str) -> str:
        return fallback


class HeaderContextualizer:
    """Tenant id from a transport metadata key (HTTP header or gRPC
    metadata); missing/empty falls back to the default network."""

    def __init__(self, header: str):
        self.header = header.lower()

    def network(self, metadata: Mapping[str, str], fallback: str) -> str:
        for k, v in metadata.items():
            if str(k).lower() == self.header and v:
                return str(v)
        return fallback


def from_config(config) -> Optional[Contextualizer]:
    header = config.get("tenancy.header", None)
    if header:
        return HeaderContextualizer(str(header))
    return None
