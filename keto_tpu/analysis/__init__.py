"""Analysis plane: the `go vet` / `golangci-lint` / `go test -race` tier.

The reference gates every merge behind vet, lint, and a dedicated race
job (ref CI .github/workflows/ci.yaml); this package is that tier for
the port, built as two halves:

  lint (ketolint)  — a stdlib-`ast` invariant checker encoding the rules
                     the codebase already lives by (lock discipline,
                     typed transport errors, config-key coverage, clock
                     discipline, host-sync purity). Pure source
                     inspection, zero third-party imports, so it runs
                     before deps are installed: `python -m
                     keto_tpu.analysis.lint`.
  lockwatch        — a runtime lock-order / blocking-under-lock detector
                     (the Python stand-in for `go test -race`): wraps
                     threading.Lock/RLock/Condition creation, tracks
                     per-thread held-lock sets, builds the global
                     acquisition-order graph, and fails the test run on
                     order-graph cycles (potential deadlock) or
                     blocking-while-holding events, with creation-site
                     stacks in the report. Enabled per-run with
                     KETO_LOCKWATCH=1 (tests/conftest.py wires the
                     pytest hooks).
  source_scan      — the one shared source-scanning helper under both
                     ketolint's config-key pass and
                     tools/check_metrics_docs.py (previously two ad-hoc
                     regex walkers).

Suppression contract (docs/architecture.md §5g): a finding is silenced
only by an in-code `# ketolint: allow[<rule>] reason=...` on (or
directly above) the offending line; an allow without a reason, or one
that suppresses nothing, is itself an error — annotations can never rot
into unreviewed noise.

This package must stay importable with NOTHING but the standard library
installed (CI runs it before `pip install`), so no keto_tpu runtime
modules and no third-party imports at module scope.
"""
