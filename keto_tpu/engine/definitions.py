"""Engine result types: the three-valued membership lattice and check
results with proof trees.

Parity with internal/check/checkgroup/definitions.go:46-74:
Membership ∈ {Unknown, IsMember, NotMember} (iota order preserved),
Result{Membership, Tree, Err}.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import IntEnum
from typing import Optional

from ..ketoapi import RelationTuple, Tree, TreeNodeType


# Subject sets whose relation is the wildcard are never expanded via
# expand-subject (ref: internal/check/engine.go:40, :124); shared by the
# host engine and the snapshot compiler so both paths stay in lockstep.
WILDCARD_RELATION = "..."


def subject_visited_key(sub) -> str:
    """Injective visited-set key. The reference keys visited subjects by
    UUID (SubjectID/SubjectSet UniqueID), which cannot collide across
    subject kinds; a display-string key would let a plain subject_id that
    textually equals a subject set's canonical form wrongly prune it."""
    from ..ketoapi import SubjectSet

    if isinstance(sub, SubjectSet):
        return f"set:{sub}"
    return f"id:{sub}"


def paginate_names(
    names: list, page_size: int, page_token: str
) -> tuple[list, str]:
    """Offset pagination over a sorted enumeration (the reverse legs'
    ListObjects/ListSubjects): the token is the next start offset, ""
    when exhausted. Shared by the device and host engine facades — both
    must produce identical pages for the same enumeration."""
    if page_token:
        try:
            start = int(page_token)
        except ValueError:
            start = -1
        if start < 0:
            # a negative offset would slice from the tail (empty page +
            # bogus continuation token) — reject like any malformed token
            from ..errors import MalformedInputError

            raise MalformedInputError(f"invalid page token {page_token!r}")
    else:
        start = 0
    size = page_size if page_size > 0 else len(names)
    page = names[start : start + size]
    next_token = str(start + size) if start + size < len(names) else ""
    return page, next_token


class Membership(IntEnum):
    # ref: checkgroup/definitions.go:65-69 (iota: Unknown, IsMember, NotMember)
    UNKNOWN = 0
    IS_MEMBER = 1
    NOT_MEMBER = 2


@dataclass
class CheckResult:
    membership: Membership
    tree: Optional[Tree] = None
    error: Optional[Exception] = None

    @property
    def allowed(self) -> bool:
        """Unknown at the top is reported as not-a-member
        (ref: internal/check/engine.go:54-60)."""
        return self.membership == Membership.IS_MEMBER


RESULT_IS_MEMBER = CheckResult(Membership.IS_MEMBER)
RESULT_NOT_MEMBER = CheckResult(Membership.NOT_MEMBER)
RESULT_UNKNOWN = CheckResult(Membership.UNKNOWN)


def leaf(t: RelationTuple) -> Tree:
    return Tree(type=TreeNodeType.LEAF, tuple=t)


def with_edge(edge_type: TreeNodeType, edge_tuple: RelationTuple, result: CheckResult) -> CheckResult:
    """Wrap a child result's tree in an edge node, mirroring
    checkgroup.WithEdge (checkgroup/definitions.go:101-124)."""
    if result.tree is None:
        tree = leaf(edge_tuple)
    else:
        tree = Tree(type=edge_type, tuple=edge_tuple, children=[result.tree])
    return CheckResult(result.membership, tree, result.error)
