"""Served OpenAPI spec (VERDICT r2 item 9): the document is generated
from the router's route constants and served at /.well-known/openapi.json
on the read and write routers; REAL response payloads from the live
daemon must validate against the spec's schemas."""

import json
import urllib.error
import urllib.request

import jsonschema
import pytest

from keto_tpu.api.daemon import Daemon
from keto_tpu.api.rest_server import SPEC_ROUTE
from keto_tpu.config import Config
from keto_tpu.ketoapi import RelationTuple
from keto_tpu.namespace import Namespace
from keto_tpu.registry import Registry


@pytest.fixture(scope="module")
def daemon():
    cfg = Config({
        "dsn": "memory",
        "serve": {
            "read": {"host": "127.0.0.1", "port": 0},
            "write": {"host": "127.0.0.1", "port": 0},
            "metrics": {"host": "127.0.0.1", "port": 0},
        },
    })
    cfg.set_namespaces([Namespace(name="files")])
    reg = Registry(cfg)
    reg.relation_tuple_manager().write_relation_tuples([
        RelationTuple.from_string("files:doc#owner@alice"),
        RelationTuple.from_string("files:doc#viewer@(files:doc#owner)"),
    ])
    d = Daemon(reg)
    d.start()
    yield d
    d.stop()


def _get(port, path):
    return urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=30)


def _schema_for(spec, path, method, code):
    resp = spec["paths"][path][method]["responses"][str(code)]
    schema = dict(resp["content"]["application/json"]["schema"])
    # resolve against the full component set
    schema["components"] = spec["components"]
    return schema


class TestServedSpec:
    def test_spec_served_on_read_and_write(self, daemon):
        """Each port's spec advertises only routes THAT port answers."""
        read = json.load(_get(daemon.read_port, SPEC_ROUTE))
        write = json.load(_get(daemon.write_port, SPEC_ROUTE))
        assert read["openapi"].startswith("3.")
        assert "/relation-tuples/check" in read["paths"]
        assert "/admin/relation-tuples" not in read["paths"]
        assert "/admin/relation-tuples" in write["paths"]
        assert "/relation-tuples/check" not in write["paths"]

    def test_spec_routes_match_router_constants(self, daemon):
        from keto_tpu.api import rest_server as r

        read = json.load(_get(daemon.read_port, SPEC_ROUTE))
        write = json.load(_get(daemon.write_port, SPEC_ROUTE))
        for route in (
            r.READ_ROUTE_BASE, r.CHECK_ROUTE_BASE, r.CHECK_OPENAPI_ROUTE,
            r.EXPAND_ROUTE, r.ALIVE_PATH, r.READY_PATH, r.VERSION_PATH,
        ):
            assert route in read["paths"], route
        for route in (
            r.WRITE_ROUTE_BASE, r.ALIVE_PATH, r.READY_PATH, r.VERSION_PATH,
        ):
            assert route in write["paths"], route

    @pytest.mark.parametrize("path,method,code,live", [
        ("/relation-tuples/check/openapi", "get",
         200, "/relation-tuples/check/openapi?namespace=files&object=doc"
              "&relation=owner&subject_id=alice"),
        ("/relation-tuples", "get",
         200, "/relation-tuples?namespace=files"),
        ("/relation-tuples/expand", "get",
         200, "/relation-tuples/expand?namespace=files&object=doc"
              "&relation=viewer&max-depth=3"),
        ("/version", "get", 200, "/version"),
        ("/health/alive", "get", 200, "/health/alive"),
    ])
    def test_live_payloads_validate(self, daemon, path, method, code, live):
        spec = json.load(_get(daemon.read_port, SPEC_ROUTE))
        payload = json.load(_get(daemon.read_port, live))
        schema = _schema_for(spec, path, method, code)
        jsonschema.Draft7Validator(schema).validate(payload)

    def test_error_payload_validates(self, daemon):
        spec = json.load(_get(daemon.read_port, SPEC_ROUTE))
        try:
            _get(daemon.read_port, "/relation-tuples?namespace=absent")
            payload = None
        except urllib.error.HTTPError as e:
            payload = json.load(e)
        assert payload is not None
        schema = _schema_for(spec, "/relation-tuples", "get", 404)
        jsonschema.Draft7Validator(schema).validate(payload)


class TestClientGenerator:
    """tools/openapi_client_gen.py guarantees: bad documents fail
    generation loudly; generated validators reject non-conforming
    bodies (the properties the e2e openapi-gen leg relies on)."""

    @staticmethod
    def _gen():
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "openapi_client_gen",
            os.path.join(repo, "tools", "openapi_client_gen.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _minimal_spec(self):
        return {
            "openapi": "3.0.3",
            "info": {"title": "t", "version": "1"},
            "paths": {
                "/things": {
                    "put": {
                        "operationId": "createThing",
                        "requestBody": {
                            "required": True,
                            "content": {"application/json": {"schema": {
                                "$ref": "#/components/schemas/thing"
                            }}},
                        },
                        "responses": {"201": {"description": "made"}},
                    }
                }
            },
            "components": {"schemas": {"thing": {
                "type": "object",
                "required": ["name"],
                "properties": {
                    "name": {"type": "string"},
                    "kind": {"type": "string", "enum": ["a", "b"]},
                },
            }}},
        }

    def test_unresolvable_ref_fails_generation(self):
        gen = self._gen()
        spec = self._minimal_spec()
        spec["paths"]["/things"]["put"]["requestBody"]["content"][
            "application/json"]["schema"]["$ref"] = "#/components/schemas/ghost"
        with pytest.raises(gen.GenerationError, match="ghost"):
            gen.generate(spec)

    def test_duplicate_operation_id_fails_generation(self):
        gen = self._gen()
        spec = self._minimal_spec()
        spec["paths"]["/things"]["delete"] = {
            "operationId": "createThing",
            "responses": {"204": {"description": "gone"}},
        }
        with pytest.raises(gen.GenerationError, match="duplicate"):
            gen.generate(spec)

    def test_missing_operation_id_fails_generation(self):
        gen = self._gen()
        spec = self._minimal_spec()
        del spec["paths"]["/things"]["put"]["operationId"]
        with pytest.raises(gen.GenerationError, match="operationId"):
            gen.generate(spec)

    def test_generated_validator_rejects_bad_bodies(self):
        import types

        gen = self._gen()
        code = gen.generate(self._minimal_spec())
        mod = types.ModuleType("genclient_unit")
        exec(code, mod.__dict__)
        c = mod.Client("http://127.0.0.1:1")  # never reached: validation first
        with pytest.raises(mod.ValidationError, match="missing required 'name'"):
            c.create_thing(body={})
        with pytest.raises(mod.ValidationError, match="expected object"):
            c.create_thing(body=[1])
        with pytest.raises(mod.ValidationError, match="not in"):
            c.create_thing(body={"name": "x", "kind": "z"})

    def test_range_status_keys_and_alias_cycles(self):
        import types

        gen = self._gen()
        # 2XX range key accepted and honored
        spec = self._minimal_spec()
        spec["paths"]["/things"]["put"]["responses"] = {"2XX": {"description": "ok"}}
        code = gen.generate(spec)
        mod = types.ModuleType("genclient_range")
        exec(code, mod.__dict__)
        # junk status key rejected loudly
        spec["paths"]["/things"]["put"]["responses"] = {"teapot": {"description": "?"}}
        with pytest.raises(gen.GenerationError, match="status key"):
            gen.generate(spec)
        # top-level alias cycle rejected at generation time
        spec2 = self._minimal_spec()
        spec2["components"]["schemas"]["a"] = {"$ref": "#/components/schemas/b"}
        spec2["components"]["schemas"]["b"] = {"$ref": "#/components/schemas/a"}
        with pytest.raises(gen.GenerationError, match="cycle"):
            gen.generate(spec2)
