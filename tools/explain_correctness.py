#!/usr/bin/env python
"""Explain-plane correctness smoke (CI) + the hot-path A/B artifact.

Differential contract, enforced with zero tolerated mismatches across
memory AND sqlite stores under write churn:

  - every engine verdict (explain path) equals the exact host oracle;
  - every ALLOW's witness path replays step-by-step through the store
    (engine/explain.replay_witness) to the same verdict;
  - every DENY's exhaustion summary equals an independent oracle walk;
  - witness_consistent holds on every quiet-store explain (the tool is
    single-threaded: no witness_racy excuses here);
  - graph families: random, deep-20 chain, cycles, AND/NOT islands —
    the acceptance list.

`--artifact out.json` additionally measures the hot-path cost of the
explain plumbing and the explain slow path itself:

  - flat check_batch throughput with the sink plumbing DORMANT (sink
    None — the serving hot path as shipped) vs ACTIVE (a live per-item
    sink list), per-call alternated medians: the dormant-vs-active
    ratio bounds the plumbing's cost from ABOVE (pre-PR code is the
    dormant path minus one dict-get per resolve), and the acceptance
    bar is 2%;
  - explain_check per-call ms (the documented slow path);
  - the committed same-backend baseline's flat qps as a cross-run
    reference (ratio reported, not gated — different boxes).
"""

from __future__ import annotations

import json
import os
import random
import statistics
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from keto_tpu.config import Config  # noqa: E402
from keto_tpu.engine.explain import replay_witness  # noqa: E402
from keto_tpu.engine.reference import ReferenceEngine  # noqa: E402
from keto_tpu.engine.tpu_engine import TPUCheckEngine  # noqa: E402
from keto_tpu.ketoapi import RelationQuery, RelationTuple  # noqa: E402
from keto_tpu.namespace import Namespace  # noqa: E402
from keto_tpu.namespace.ast import (  # noqa: E402
    ComputedSubjectSet,
    InvertResult,
    Operator,
    Relation,
    SubjectSetRewrite,
)

NID = "default"

NAMESPACES = [
    Namespace(name="files"),
    Namespace(name="groups"),
    Namespace(name="acl", relations=[
        Relation(name="allow"),
        Relation(name="deny"),
        Relation(name="access", subject_set_rewrite=SubjectSetRewrite(
            operation=Operator.AND,
            children=[
                ComputedSubjectSet(relation="allow"),
                InvertResult(child=ComputedSubjectSet(relation="deny")),
            ])),
    ]),
]

CHECKED = {"checks": 0, "allows": 0, "denies": 0, "replays": 0}


def _manager(kind: str, tmpdir: str):
    if kind == "memory":
        from keto_tpu.storage.memory import MemoryManager

        return MemoryManager()
    from keto_tpu.storage.sqlite import SQLPersister

    return SQLPersister(f"sqlite://{tmpdir}/explain_{id(tmpdir)}.db")


def _graph_families(rng: random.Random):
    """[(name, tuples, queries)] — the acceptance graph list."""
    fams = []
    # random group/file graphs
    groups = [f"g{i}" for i in range(8)]
    users = ["u1", "u2", "u3", "ghost"]
    tuples = set()
    for g in groups:
        for u in users[:3]:
            if rng.random() < 0.3:
                tuples.add(f"groups:{g}#member@{u}")
        other = rng.choice(groups)
        if other != g and rng.random() < 0.6:
            tuples.add(f"groups:{g}#member@(groups:{other}#member)")
    for i in range(6):
        tuples.add(f"files:f{i}#owner@(groups:{rng.choice(groups)}#member)")
    queries = [
        RelationTuple("files", f"f{i}", "owner", subject_id=u)
        for i in range(6) for u in users
    ]
    fams.append(("random", sorted(tuples), queries))
    # deep-20 chain
    chain = ["groups:c0#member@u1"] + [
        f"groups:c{i}#member@(groups:c{i - 1}#member)" for i in range(1, 21)
    ]
    fams.append(("deep20", chain, [
        RelationTuple("groups", "c20", "member", subject_id=u)
        for u in ("u1", "u2")
    ]))
    # cycle
    fams.append(("cycle", [
        "groups:a#member@(groups:b#member)",
        "groups:b#member@(groups:a#member)",
        "groups:b#member@u1",
    ], [
        RelationTuple("groups", g, "member", subject_id=u)
        for g in ("a", "b") for u in ("u1", "u2")
    ]))
    # AND/NOT islands
    fams.append(("islands", [
        "acl:d1#allow@u1", "acl:d2#allow@u1", "acl:d2#deny@u1",
    ], [
        RelationTuple("acl", d, "access", subject_id=u)
        for d in ("d1", "d2") for u in ("u1", "u2")
    ]))
    return fams


def _assert(cond, msg):
    if not cond:
        print(f"explain_correctness: FAIL — {msg}")
        sys.exit(1)


def _check_one(engine, oracle, manager, t):
    res, trace = engine.explain_check(t)
    want = oracle.check_relation_tuple(t, 0, NID)
    CHECKED["checks"] += 1
    if want.error is not None:
        _assert(res.error is not None, f"error parity at {t}")
        return
    _assert(res.error is None, f"unexpected error at {t}: {res.error}")
    _assert(
        res.allowed == want.allowed,
        f"verdict mismatch at {t}: engine={res.allowed} oracle={want.allowed}",
    )
    _assert(
        trace["witness_consistent"],
        f"witness inconsistent on a quiet store at {t}: {trace}",
    )
    if res.allowed:
        CHECKED["allows"] += 1
        _assert(trace["witness"], f"ALLOW without witness at {t}")
        _assert(
            replay_witness(manager, t, trace["witness"], NID),
            f"witness replay failed at {t}: {trace['witness']}",
        )
        CHECKED["replays"] += 1
    else:
        CHECKED["denies"] += 1
        walk = oracle.explain_check(t, 0, NID)
        _assert(
            trace["exhaustion"] == walk["exhaustion"],
            f"exhaustion mismatch at {t}: {trace['exhaustion']} "
            f"vs {walk['exhaustion']}",
        )


def run_store(kind: str, tmpdir: str):
    rng = random.Random(14)
    manager = _manager(kind, tmpdir)
    cfg = Config({"limit": {"max_read_depth": 25}})
    cfg.set_namespaces(NAMESPACES)
    for name, tuples, queries in _graph_families(rng):
        manager.delete_all_relation_tuples(RelationQuery(), nid=NID)
        manager.write_relation_tuples(
            [RelationTuple.from_string(s) for s in tuples], nid=NID
        )
        engine = TPUCheckEngine(manager, cfg)
        oracle = ReferenceEngine(manager, cfg, visited_pruning=False)
        for t in queries:
            _check_one(engine, oracle, manager, t)
        # churn: delete/re-add an edge mid-family, re-verify everything
        victim = RelationTuple.from_string(tuples[0])
        manager.delete_relation_tuples([victim], nid=NID)
        for t in queries:
            _check_one(engine, oracle, manager, t)
        manager.write_relation_tuples([victim], nid=NID)
        for t in queries:
            _check_one(engine, oracle, manager, t)
        print(f"explain_correctness: {kind}/{name} ok")
    close = getattr(manager, "close", None)
    if close:
        close()


AB_CALLS = 40
AB_BATCH = 256


def measure_artifact() -> dict:
    """The hot-path A/B: flat check_batch with the explain sink DORMANT
    vs ACTIVE, per-call alternated medians over identical batches."""
    from keto_tpu.storage.memory import MemoryManager

    rng = random.Random(7)
    manager = MemoryManager()
    users = [f"u{i}" for i in range(64)]
    tuples = [
        RelationTuple("files", f"f{i}", "owner",
                      subject_id=rng.choice(users))
        for i in range(2048)
    ]
    manager.write_relation_tuples(tuples, nid=NID)
    cfg = Config({"limit": {"max_read_depth": 8}})
    cfg.set_namespaces(NAMESPACES)
    engine = TPUCheckEngine(manager, cfg)
    batch = [
        RelationTuple("files", f"f{rng.randrange(2048)}", "owner",
                      subject_id=rng.choice(users))
        for _ in range(AB_BATCH)
    ]
    engine.check_batch(batch)  # compile + state build outside the clock
    dormant, active = [], []
    for i in range(AB_CALLS * 2):
        sink = None if i % 2 == 0 else [None] * AB_BATCH
        t0 = time.perf_counter()
        handle = engine.check_batch_submit(batch, explain_sink=sink)
        engine.check_batch_resolve(handle)
        dt = time.perf_counter() - t0
        (dormant if sink is None else active).append(dt)
    m_dormant = statistics.median(dormant)
    m_active = statistics.median(active)
    t0 = time.perf_counter()
    for t in batch[:20]:
        engine.explain_check(t)
    explain_ms = (time.perf_counter() - t0) / 20 * 1e3
    flat_qps = AB_BATCH / m_dormant
    record = {
        "metric": "explain_ab",
        "ab_calls_per_arm": AB_CALLS,
        "batch": AB_BATCH,
        "flat_qps_sink_dormant": round(flat_qps, 1),
        "flat_qps_sink_active": round(AB_BATCH / m_active, 1),
        "sink_active_vs_dormant": round(m_active / m_dormant, 4),
        "explain_check_per_call_ms": round(explain_ms, 3),
        "device": "cpu",
    }
    baseline_path = os.path.join(REPO, "BENCH_r10_cpu.json")
    if os.path.exists(baseline_path):
        base = json.load(open(baseline_path))
        record["baseline_flat_qps_bench_r10"] = base.get("value")
        if base.get("value"):
            record["vs_baseline_cross_run"] = round(
                flat_qps / base["value"], 3
            )
    _assert(
        record["sink_active_vs_dormant"] <= 1.02
        or m_active - m_dormant < 0.0005,
        f"explain plumbing cost over the 2% bar: {record}",
    )
    return record


def main() -> int:
    artifact_path = None
    if "--artifact" in sys.argv:
        artifact_path = sys.argv[sys.argv.index("--artifact") + 1]
    with tempfile.TemporaryDirectory() as tmpdir:
        for kind in ("memory", "sqlite"):
            run_store(kind, tmpdir)
    print(f"explain_correctness: differential totals {CHECKED}")
    _assert(CHECKED["allows"] > 0 and CHECKED["denies"] > 0,
            "degenerate suite: need both verdicts exercised")
    if artifact_path:
        record = measure_artifact()
        with open(artifact_path, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"explain_correctness: artifact -> {artifact_path}")
        print(json.dumps(record))
    print("explain_correctness: ALL GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
