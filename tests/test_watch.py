"""Watch subsystem (keto_tpu/watch): the streaming changelog.

Covers the hub contract (resumable snaptoken cursors: every change
strictly after the token, exactly once, in version order; bounded ring
buffers with explicit RESET, never silent drops), the resumable-cursor
differential suite (random write churn, watcher killed and resumed
mid-stream, forced overflow) at the hub level AND through the gRPC, SSE,
and aio wire planes, engine push-invalidation, the retention-aware
changelog trim, CLI/metrics/config surfaces, and the REST reverse-read
snaptoken parity pin. Soak/backpressure legs are marked `slow` (excluded
from the tier-1 gate and CI's test job)."""

import json
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import grpc
import pytest

from keto_tpu.api import ReadClient, WriteClient, open_channel
from keto_tpu.api.daemon import Daemon
from keto_tpu.config import Config
from keto_tpu.engine.snaptoken import (
    SnaptokenUnsatisfiableError,
    encode_snaptoken,
    parse_snaptoken,
)
from keto_tpu.ketoapi import RelationTuple
from keto_tpu.registry import Registry
from keto_tpu.storage import MemoryManager, SQLitePersister
from keto_tpu.watch import WatchHub

NID = "default"

NAMESPACES = [
    {"name": "videos", "relations": [{"name": "owner"}]},
    {"name": "groups", "relations": [{"name": "member"}]},
]


def vt(i, user="alice"):
    return RelationTuple("videos", f"v{i}", "owner", subject_id=user)


def wait_for(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def drain(sub, n, timeout=10.0):
    """Pull n events off a subscription (or fewer on timeout)."""
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        event = sub.get(timeout=deadline - time.monotonic())
        if event is not None:
            out.append(event)
    return out


def changes_of(events):
    """Flatten events to comparable (version, op, tuple-string) triples."""
    return [
        (e.version, op, str(t)) for e in events for op, t in e.changes
    ]


def oracle_since(manager, version, nid=NID):
    """The store's own changelog as the expected triple sequence."""
    return [
        (v, op, str(t))
        for v, op, t in manager.changelog_since(version, nid=nid)
    ]


# -- hub core -----------------------------------------------------------------


class TestHubCore:
    def make(self, **kw):
        m = MemoryManager()
        hub = WatchHub(m, poll_interval=0.05, **kw)
        return m, hub

    def test_live_tail_in_version_order(self):
        m, hub = self.make()
        sub = hub.subscribe(NID)
        m.write_relation_tuples([vt(0)])
        m.transact_relation_tuples([vt(1), vt(2)], [vt(0)])
        events = drain(sub, 2)
        assert [e.kind for e in events] == ["change", "change"]
        assert changes_of(events) == oracle_since(m, 0)
        # the snaptoken IS the version cursor
        assert parse_snaptoken(events[-1].snaptoken, NID) == m.version(nid=NID)
        sub.close()

    def test_resume_replays_exactly_once(self):
        m, hub = self.make()
        for i in range(6):
            m.write_relation_tuples([vt(i)])
        sub = hub.subscribe(NID, min_version=2)
        m.write_relation_tuples([vt(6)])  # live event after the replay
        events = drain(sub, 5)
        assert changes_of(events) == oracle_since(m, 2)
        sub.close()

    def test_token_ahead_of_store_raises(self):
        m, hub = self.make()
        m.write_relation_tuples([vt(0)])
        with pytest.raises(SnaptokenUnsatisfiableError):
            hub.subscribe(NID, min_version=99)

    def test_live_subscription_starts_at_current_version(self):
        m, hub = self.make()
        m.write_relation_tuples([vt(0)])
        sub = hub.subscribe(NID)
        assert sub.get(timeout=0.2) is None  # history not replayed
        m.write_relation_tuples([vt(1)])
        events = drain(sub, 1)
        assert changes_of(events) == oracle_since(m, 1)
        sub.close()

    def test_nid_isolation(self):
        m, hub = self.make()
        sub = hub.subscribe(NID)
        m.write_relation_tuples([vt(0)], nid="tenant-b")
        m.write_relation_tuples([vt(1)])
        events = drain(sub, 1)
        assert changes_of(events) == [(1, "insert", "videos:v1#owner@alice")]
        assert sub.get(timeout=0.2) is None
        sub.close()

    def test_overflow_resets_then_resumes_live(self):
        m, hub = self.make()
        sub = hub.subscribe(NID, buffer=2)
        for i in range(8):
            m.write_relation_tuples([vt(i)])
        state = hub._states[NID]
        assert wait_for(lambda: state.tail_version == 8)
        event = sub.get(timeout=5)
        assert event.is_reset  # overflow is explicit, never a silent drop
        assert parse_snaptoken(event.snaptoken, NID) == 8
        m.write_relation_tuples([vt(100)])
        events = drain(sub, 1)
        assert changes_of(events) == [(9, "insert", "videos:v100#owner@alice")]
        sub.close()

    def test_replay_larger_than_buffer_does_not_reset(self):
        # a resume gap the changelog still covers must deliver in full,
        # however small the live ring: the replay rides the backlog,
        # not the backpressure ring
        m, hub = self.make()
        for i in range(30):
            m.write_relation_tuples([vt(i)])
        sub = hub.subscribe(NID, min_version=0, buffer=4)
        events = drain(sub, 30)
        assert [e.kind for e in events] == ["change"] * 30
        assert changes_of(events) == oracle_since(m, 0)
        sub.close()

    def test_truncated_changelog_resets_on_subscribe(self, monkeypatch):
        from keto_tpu.storage import memory as memmod

        monkeypatch.setattr(memmod, "CHANGE_LOG_CAP", 8)
        m = memmod.MemoryManager()
        hub = WatchHub(m, poll_interval=0.05)
        for i in range(12):  # deque evicts versions 1-4
            m.write_relation_tuples([vt(i)])
        sub = hub.subscribe(NID, min_version=2)
        event = sub.get(timeout=5)
        assert event.is_reset
        assert parse_snaptoken(event.snaptoken, NID) == 12
        sub.close()

    def test_truncated_changelog_resets_live_tail(self, monkeypatch):
        from keto_tpu.storage import memory as memmod

        monkeypatch.setattr(memmod, "CHANGE_LOG_CAP", 8)
        m = memmod.MemoryManager()
        hub = WatchHub(m, poll_interval=0.2)
        m.write_relation_tuples([vt(0)])
        sub = hub.subscribe(NID)
        # detach the event-driven hook so the tailer only polls: the
        # burst below wraps the 8-slot log between polls, so the next
        # drain finds a gap it cannot bridge -> in-band RESET
        m._write_listeners.clear()
        for i in range(1, 12):
            m.write_relation_tuples([vt(i)])
        events = drain(sub, 1)
        assert events and events[0].is_reset
        sub.close()

    def test_namespace_filter(self):
        m, hub = self.make()
        sub = hub.subscribe(NID)
        m.write_relation_tuples([vt(1)])
        m.write_relation_tuples(
            [RelationTuple("groups", "g1", "member", subject_id="bob")]
        )
        events = drain(sub, 2)
        kept = [e.filtered("groups") for e in events]
        assert kept[0] is None
        assert [str(t) for _, t in kept[1].changes] == ["groups:g1#member@bob"]
        # RESET survives any filter
        reset = hub._reset_event(NID, 5)
        assert reset.filtered("groups") is reset
        sub.close()

    def test_min_active_version_tracks_cursors(self):
        m, hub = self.make()
        assert hub.min_active_version(NID) is None
        m.write_relation_tuples([vt(0)])
        sub = hub.subscribe(NID)
        assert hub.min_active_version(NID) == 1
        m.write_relation_tuples([vt(1)])
        state = hub._states[NID]
        assert wait_for(lambda: state.tail_version == 2)
        # cursor trails until the subscriber consumes
        assert hub.min_active_version(NID) == 1
        drain(sub, 1)
        assert hub.min_active_version(NID) == 2
        sub.close()
        assert hub.min_active_version(NID) is None

    def test_stop_closes_subscribers(self):
        m, hub = self.make()
        sub = hub.subscribe(NID)
        hub.stop()
        assert sub.closed
        assert sub.get(timeout=0.1) is None
        with pytest.raises(RuntimeError):
            hub.subscribe(NID)


# -- resumable-cursor differential (hub level) --------------------------------


class TestResumableDifferential:
    def churn(self, m, rng, steps, pool=40):
        """Random single-op write churn; idempotent no-ops don't commit."""
        for _ in range(steps):
            i = rng.randrange(pool)
            if rng.random() < 0.35:
                m.delete_relation_tuples([vt(i)])
            else:
                m.write_relation_tuples([vt(i)])

    def test_kill_and_resume_mid_stream_matches_oracle(self):
        rng = random.Random(7)
        m = MemoryManager()
        # buffer > total churn: a replay after a long gap must not
        # overflow (the forced-overflow path has its own test below)
        hub = WatchHub(m, poll_interval=0.02, buffer=2048)
        received = []
        last_token = encode_snaptoken(0, NID)
        stop = threading.Event()

        def writer():
            self.churn(m, rng, 300)
            stop.set()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        # consume in short-lived sessions: each one killed mid-stream and
        # resumed from the last fully-consumed event's snaptoken
        for _session in range(50):
            sub = hub.subscribe(
                NID, min_version=parse_snaptoken(last_token, NID)
            )
            for _ in range(rng.randrange(1, 8)):
                event = sub.get(timeout=0.05)
                if event is None:
                    break
                assert not event.is_reset
                received.append(event)
                last_token = event.snaptoken
            sub.close()  # the kill
            if stop.is_set() and parse_snaptoken(
                last_token, NID
            ) == m.version(nid=NID):
                break
        t.join(timeout=10)
        # drain the tail in one final session
        sub = hub.subscribe(NID, min_version=parse_snaptoken(last_token, NID))
        while parse_snaptoken(last_token, NID) < m.version(nid=NID):
            event = sub.get(timeout=5)
            assert event is not None and not event.is_reset
            received.append(event)
            last_token = event.snaptoken
        sub.close()
        # exactly the oracle sequence: no gaps, no duplicates, in order
        assert changes_of(received) == oracle_since(m, 0)

    def test_forced_overflow_ends_in_reset_and_recovers(self):
        rng = random.Random(13)
        m = MemoryManager()
        hub = WatchHub(m, poll_interval=0.02)
        sub = hub.subscribe(NID, buffer=4)
        self.churn(m, rng, 60)  # unconsumed: must overflow a 4-slot ring
        state = hub._states[NID]
        assert wait_for(lambda: state.tail_version == m.version(nid=NID))
        event = sub.get(timeout=5)
        assert event.is_reset
        reset_version = parse_snaptoken(event.snaptoken, NID)
        assert reset_version == m.version(nid=NID)
        # after the reset the stream is exact again (stay under the
        # 4-slot ring this time — the un-drained churn would just
        # overflow it again, correctly)
        self.churn(m, rng, 3)
        received = []
        while parse_snaptoken(
            (received[-1].snaptoken if received else event.snaptoken), NID
        ) < m.version(nid=NID):
            nxt = sub.get(timeout=5)
            assert nxt is not None and not nxt.is_reset
            received.append(nxt)
        assert changes_of(received) == oracle_since(m, reset_version)
        sub.close()

    @pytest.mark.slow
    def test_soak_churn_with_subscriber_fleet(self):
        """Backpressure soak: sustained churn against a fleet of
        subscribers with mixed buffer sizes — big buffers must observe
        the exact oracle; tiny ones must recover through RESETs with no
        silent gaps in between."""
        rng = random.Random(99)
        m = MemoryManager()
        hub = WatchHub(m, poll_interval=0.01)
        results = {}

        def consume(name, buffer, lag):
            sub = hub.subscribe(NID, min_version=0, buffer=buffer)
            seen, resets = [], 0
            anchor = 0
            while True:
                event = sub.get(timeout=2.0)
                if event is None:
                    break
                if event.is_reset:
                    resets += 1
                    anchor = event.version
                    seen = []
                else:
                    seen.append(event)
                if lag:
                    time.sleep(lag)
            sub.close()
            results[name] = (anchor, seen, resets)

        threads = [
            threading.Thread(
                target=consume, args=(name, buf, lag), daemon=True
            )
            for name, buf, lag in (
                ("fast", 1 << 16, 0),
                ("medium", 1 << 16, 0.0005),
                ("tiny", 4, 0.002),
            )
        ]
        for t in threads:
            t.start()
        self.churn(m, rng, 5000, pool=200)
        for t in threads:
            t.join(timeout=120)
        for name in ("fast", "medium"):
            anchor, seen, resets = results[name]
            assert resets == 0, name
            assert changes_of(seen) == oracle_since(m, anchor), name
        anchor, seen, resets = results["tiny"]
        assert resets >= 1  # the 4-slot ring cannot survive 5000 events
        assert changes_of(seen) == oracle_since(m, anchor)


# -- retention-aware durable changelog trim -----------------------------------


class TestRetentionTrim:
    def rows(self, p):
        return p._conn.execute(
            "SELECT COUNT(*) FROM keto_change_log"
        ).fetchone()[0]

    def test_active_cursor_pins_rows_past_soft_cap(self):
        p = SQLitePersister("memory")
        p.CHANGE_LOG_CAP = 8
        hub = WatchHub(p, poll_interval=0.05)
        sub = hub.subscribe(NID)  # cursor at v0
        for i in range(20):
            p.write_relation_tuples([vt(i)])
        # guard (cursor 0) holds every row the cursor may still need
        assert self.rows(p) == 20
        # resuming from the pinned cursor still sees complete history
        assert len(oracle_since(p, 0)) == 20
        # consume everything -> cursor advances -> next write trims
        drain(sub, 20)
        assert sub.cursor == 20
        p.write_relation_tuples([vt(100)])
        assert self.rows(p) <= p.CHANGE_LOG_CAP + 1
        sub.close()

    def test_no_cursor_trims_at_soft_cap(self):
        p = SQLitePersister("memory")
        p.CHANGE_LOG_CAP = 8
        WatchHub(p, poll_interval=0.05)  # guard wired, nobody subscribed
        for i in range(20):
            p.write_relation_tuples([vt(i)])
        assert self.rows(p) <= 9  # OFFSET-cap trim keeps cap(+1) rows

    def test_stuck_cursor_bounded_by_hard_cap(self):
        p = SQLitePersister("memory")
        p.CHANGE_LOG_CAP = 4
        hub = WatchHub(p, poll_interval=0.05)
        sub = hub.subscribe(NID)  # never consumes: cursor stuck at 0
        for i in range(40):
            p.write_relation_tuples([vt(i)])
        hard = p.CHANGE_LOG_CAP * p.CHANGE_LOG_HARD_FACTOR
        assert self.rows(p) <= hard + 1
        # the stuck cursor's history is gone: resume is an explicit RESET
        sub2 = hub.subscribe(NID, min_version=1)
        event = sub2.get(timeout=5)
        assert event.is_reset
        sub.close()
        sub2.close()

    def test_broken_guard_never_fails_writes(self):
        p = SQLitePersister("memory")
        p.set_trim_guard(lambda nid: 1 / 0)
        p.write_relation_tuples([vt(0)])  # must not raise
        assert p.version(nid=NID) == 1


# -- engine push-invalidation -------------------------------------------------


class TestEnginePushInvalidation:
    def test_hub_commit_pokes_device_mirror(self):
        cfg = Config(
            {"dsn": "memory", "check": {"engine": "tpu"},
             "namespaces": NAMESPACES}
        )
        reg = Registry(cfg)
        reg.watch_hub()
        engine = reg.check_engine()
        engine._ensure_state()
        v0 = engine._state.covered_version
        m = reg.relation_tuple_manager()
        m.write_relation_tuples([vt(0)])
        m.write_relation_tuples([vt(1)])
        # covered_version advances with NO check call: the write hook's
        # hub event drove the refresh off the request path
        assert wait_for(
            lambda: engine._state.covered_version >= v0 + 2, timeout=10
        )
        assert engine.stats.get("push_refreshes", 0) >= 1

    def test_unbuilt_tenant_engines_not_materialized(self):
        cfg = Config(
            {"dsn": "memory", "check": {"engine": "tpu"},
             "namespaces": NAMESPACES}
        )
        reg = Registry(cfg)
        reg.watch_hub()
        reg.relation_tuple_manager().write_relation_tuples(
            [vt(0)], nid="tenant-z"
        )
        time.sleep(0.1)
        assert "tenant-z" not in reg._nid_engines


# -- wire planes --------------------------------------------------------------


def make_daemon(aio=False):
    read = {"host": "127.0.0.1", "port": 0}
    if aio:
        read["grpc"] = {"host": "127.0.0.1", "port": 0, "aio": True}
    cfg = Config(
        {
            "dsn": "memory",
            "check": {"engine": "host"},
            "serve": {
                "read": read,
                "write": {"host": "127.0.0.1", "port": 0},
                "metrics": {"host": "127.0.0.1", "port": 0},
            },
            "namespaces": NAMESPACES,
            "watch": {"poll_interval": 0.05},
        }
    )
    return Daemon(Registry(cfg))


@pytest.fixture(scope="module")
def daemon():
    d = make_daemon()
    d.start()
    yield d
    d.stop()


@pytest.fixture(scope="module")
def aio_daemon():
    d = make_daemon(aio=True)
    d.start()
    yield d
    d.stop()


@pytest.fixture
def clients(daemon):
    rc = ReadClient(open_channel(f"127.0.0.1:{daemon.read_port}"))
    wc = WriteClient(open_channel(f"127.0.0.1:{daemon.write_port}"))
    yield rc, wc
    rc.close()
    wc.close()


def stream_collect(client, n, snaptoken="", namespace="", out=None):
    """Consume n events off ReadClient.watch in a daemon thread."""
    out = [] if out is None else out

    def run():
        try:
            for event in client.watch(snaptoken=snaptoken, namespace=namespace):
                out.append(event)
                if len(out) >= n:
                    break
        except grpc.RpcError:
            pass

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return out, t


def grpc_triples(events, nid=NID):
    return [
        (parse_snaptoken(e.snaptoken, nid), op, str(t))
        for e in events
        for op, t in e.changes
    ]


class _GrpcWatchSuite:
    """The resumable-cursor differential through a gRPC plane; the aio
    subclass only swaps the daemon (same ReadClient, same contract)."""

    @pytest.fixture
    def rig(self, daemon):
        rc = ReadClient(open_channel(f"127.0.0.1:{daemon.read_port}"))
        wc = WriteClient(open_channel(f"127.0.0.1:{daemon.write_port}"))
        yield daemon, rc, wc
        rc.close()
        wc.close()

    def test_live_tail(self, rig):
        daemon, rc, wc = rig
        manager = daemon.registry.relation_tuple_manager()
        v0 = manager.version(nid=NID)
        hub = daemon.registry.watch_hub()
        before = len(hub._states[NID].subs) if NID in hub._states else 0
        out, t = stream_collect(rc, 2)
        assert wait_for(
            lambda: NID in hub._states
            and len(hub._states[NID].subs) > before
        )
        wc.transact(insert=[vt(0, "livetail")])
        wc.transact(delete=[vt(0, "livetail")])
        t.join(timeout=10)
        assert grpc_triples(out) == oracle_since(manager, v0)

    def test_kill_resume_differential(self, rig):
        daemon, rc, wc = rig
        manager = daemon.registry.relation_tuple_manager()
        rng = random.Random(21)
        v0 = manager.version(nid=NID)
        last_token = encode_snaptoken(v0, NID)
        received = []
        for _session in range(12):
            # churn between sessions: these commits land while no
            # watcher is connected and must still arrive exactly once
            for _ in range(rng.randrange(1, 5)):
                i = rng.randrange(12)
                if rng.random() < 0.4:
                    wc.transact(delete=[vt(i, "diff")])
                else:
                    wc.transact(insert=[vt(i, "diff")])
            behind = manager.version(nid=NID) - parse_snaptoken(
                last_token, NID
            )
            if not behind:
                continue
            # consume a random prefix, then kill the stream (max_events
            # cancels the RPC mid-history)
            for event in rc.watch(
                snaptoken=last_token,
                max_events=min(rng.randrange(1, 4), behind),
            ):
                assert event.event_type == "change"
                received.append(event)
                last_token = event.snaptoken
        behind = manager.version(nid=NID) - parse_snaptoken(last_token, NID)
        if behind:  # final catch-up session
            for event in rc.watch(snaptoken=last_token, max_events=behind):
                received.append(event)
                last_token = event.snaptoken
        assert grpc_triples(received) == oracle_since(manager, v0)

    def test_truncated_history_is_explicit_reset(self, rig):
        daemon, rc, wc = rig
        manager = daemon.registry.relation_tuple_manager()
        wc.transact(insert=[vt(0, "trunc")])
        old = encode_snaptoken(manager.version(nid=NID), NID)
        wc.transact(insert=[vt(1, "trunc")])
        wc.transact(delete=[vt(0, "trunc"), vt(1, "trunc")])
        # wipe the changelog's history under the old token (pad entries
        # carry the current version, so the store can no longer prove
        # completeness back to `old`): the resume MUST reset
        current = manager.version(nid=NID)
        net = manager._networks[NID]
        with manager._lock:
            net.log.extend(
                (current, "pad", None) for _ in range(net.log.maxlen or 0)
            )
        out = list(rc.watch(snaptoken=old, max_events=1))
        assert out and out[0].event_type == "reset"
        assert out[0].changes == []
        assert parse_snaptoken(out[0].snaptoken, NID) == current

    def test_token_ahead_is_failed_precondition(self, rig):
        daemon, rc, _wc = rig
        ahead = encode_snaptoken(10**9, NID)
        with pytest.raises(grpc.RpcError) as err:
            for _ in rc.watch(snaptoken=ahead):
                break
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION

    def test_malformed_token_is_invalid_argument(self, rig):
        daemon, rc, _wc = rig
        with pytest.raises(grpc.RpcError) as err:
            for _ in rc.watch(snaptoken="zzzz_not_a_token"):
                break
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    def test_namespace_filter(self, rig):
        daemon, rc, wc = rig
        hub = daemon.registry.watch_hub()
        before = len(hub._states[NID].subs) if NID in hub._states else 0
        out, t = stream_collect(rc, 1, namespace="groups")
        assert wait_for(
            lambda: NID in hub._states
            and len(hub._states[NID].subs) > before
        )
        wc.transact(insert=[vt(50, "filter")])
        wc.transact(
            insert=[RelationTuple("groups", "g9", "member", subject_id="f")]
        )
        t.join(timeout=10)
        assert [str(t_) for e in out for _, t_ in e.changes] == [
            "groups:g9#member@f"
        ]


class TestWatchGRPC(_GrpcWatchSuite):
    pass


class TestWatchAio(_GrpcWatchSuite):
    """Same differential suite against the loop-native aio plane (the
    direct read-gRPC listener with serve.read.grpc.aio)."""

    @pytest.fixture
    def rig(self, aio_daemon):
        rc = ReadClient(
            open_channel(f"127.0.0.1:{aio_daemon.read_grpc_port}")
        )
        wc = WriteClient(open_channel(f"127.0.0.1:{aio_daemon.write_port}"))
        yield aio_daemon, rc, wc
        rc.close()
        wc.close()


# -- SSE plane ----------------------------------------------------------------


def sse_get(port, params, timeout=15):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}/relation-tuples/watch?{qs}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        ctype = r.headers.get("Content-Type")
        body = r.read().decode()
    events, current = [], None
    for line in body.splitlines():
        if line.startswith("event: "):
            current = line[len("event: "):]
        elif line.startswith("data: "):
            events.append((current, json.loads(line[len("data: "):])))
    return ctype, events


def sse_triples(events, nid=NID):
    return [
        (parse_snaptoken(data["snaptoken"], nid), c["action"],
         str(RelationTuple.from_dict(c["relation_tuple"])))
        for _kind, data in events
        for c in data["changes"]
    ]


class TestWatchSSE:
    def test_replay_stream_shape(self, daemon, clients):
        _rc, wc = clients
        manager = daemon.registry.relation_tuple_manager()
        v0 = manager.version(nid=NID)
        wc.transact(insert=[vt(0, "sse")])
        wc.transact(insert=[vt(1, "sse")])
        ctype, events = sse_get(
            daemon.read_port,
            {"snaptoken": encode_snaptoken(v0, NID), "max_events": 2},
        )
        assert ctype.startswith("text/event-stream")
        assert [kind for kind, _ in events] == ["change", "change"]
        assert sse_triples(events) == oracle_since(manager, v0)

    def test_kill_resume_differential(self, daemon, clients):
        _rc, wc = clients
        manager = daemon.registry.relation_tuple_manager()
        rng = random.Random(31)
        v0 = manager.version(nid=NID)
        last_token = encode_snaptoken(v0, NID)
        received = []
        for _session in range(8):
            for _ in range(rng.randrange(1, 4)):
                i = rng.randrange(10)
                if rng.random() < 0.4:
                    wc.transact(delete=[vt(i, "ssediff")])
                else:
                    wc.transact(insert=[vt(i, "ssediff")])
            want = rng.randrange(1, 3)
            behind = manager.version(nid=NID) - parse_snaptoken(
                last_token, NID
            )
            if not behind:
                continue
            _ctype, events = sse_get(
                daemon.read_port,
                {"snaptoken": last_token,
                 "max_events": min(want, behind)},
            )
            for kind, data in events:
                assert kind == "change"
                received.append((kind, data))
                last_token = data["snaptoken"]
        behind = manager.version(nid=NID) - parse_snaptoken(last_token, NID)
        if behind:
            _ctype, events = sse_get(
                daemon.read_port,
                {"snaptoken": last_token, "max_events": behind},
            )
            received.extend(events)
        assert sse_triples(received) == oracle_since(manager, v0)

    def test_namespace_filter_and_reset_passthrough(self, daemon, clients):
        _rc, wc = clients
        manager = daemon.registry.relation_tuple_manager()
        v0 = manager.version(nid=NID)
        wc.transact(insert=[vt(7, "ssefilter")])
        wc.transact(
            insert=[RelationTuple("groups", "g7", "member", subject_id="s")]
        )
        _ctype, events = sse_get(
            daemon.read_port,
            {"snaptoken": encode_snaptoken(v0, NID), "namespace": "groups",
             "max_events": 1},
        )
        assert [kind for kind, _ in events] == ["change"]
        assert [
            c["relation_tuple"]["namespace"]
            for _, d in events for c in d["changes"]
        ] == ["groups"]

    def test_bad_tokens_are_http_errors(self, daemon):
        for token, status in (
            (encode_snaptoken(10**9, NID), 409),
            ("zz_bad", 400),
        ):
            qs = urllib.parse.urlencode({"snaptoken": token})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{daemon.read_port}"
                    f"/relation-tuples/watch?{qs}",
                    timeout=10,
                )
            assert err.value.code == status

    def test_watch_route_in_read_spec(self, daemon):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{daemon.read_port}/.well-known/openapi.json",
            timeout=10,
        ) as r:
            spec = json.load(r)
        assert "/relation-tuples/watch" in spec["paths"]
        op = spec["paths"]["/relation-tuples/watch"]["get"]
        assert op["operationId"] == "getWatch"
        assert "text/event-stream" in op["responses"]["200"]["content"]


# -- CLI ----------------------------------------------------------------------


class TestWatchCLI:
    def test_watch_verb_resumes_and_prints_json(self, daemon, clients, capsys):
        from keto_tpu.cli import main as cli_main

        _rc, wc = clients
        manager = daemon.registry.relation_tuple_manager()
        v0 = manager.version(nid=NID)
        wc.transact(insert=[vt(0, "cli")])
        wc.transact(insert=[vt(1, "cli")])
        rc_code = cli_main([
            "watch",
            "--read-remote", f"127.0.0.1:{daemon.read_port}",
            "--snaptoken", encode_snaptoken(v0, NID),
            "--max-events", "2",
            "--format", "json",
        ])
        assert rc_code == 0
        lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert len(lines) == 2
        triples = [
            (parse_snaptoken(d["snaptoken"], NID), c["action"],
             str(RelationTuple.from_dict(c["relation_tuple"])))
            for d in lines for c in d["changes"]
        ]
        assert triples == oracle_since(manager, v0)

    def test_watch_verb_default_format(self, daemon, clients, capsys):
        from keto_tpu.cli import main as cli_main

        _rc, wc = clients
        manager = daemon.registry.relation_tuple_manager()
        v0 = manager.version(nid=NID)
        wc.transact(insert=[vt(9, "clitext")])
        assert cli_main([
            "watch",
            "--read-remote", f"127.0.0.1:{daemon.read_port}",
            "--snaptoken", encode_snaptoken(v0, NID),
            "--max-events", "1",
        ]) == 0
        out = capsys.readouterr().out
        assert out.startswith("INSERT\tvideos:v9#owner@clitext")


# -- limits, metrics, config --------------------------------------------------


class TestWatchLimits:
    def test_watcher_cap_shared_and_config_driven(self):
        cfg = Config(
            {
                "dsn": "memory",
                "serve": {"read": {"grpc": {"max_watchers": 3}}},
                "namespaces": NAMESPACES,
            }
        )
        from keto_tpu.api.grpc_server import _Services

        services = _Services(Registry(cfg))
        assert services.max_watchers == 3
        for _ in range(3):
            assert services._watch_slots.acquire(blocking=False)
        # 4th watcher of ANY kind (health or tuple watch) is refused
        assert not services._watch_slots.acquire(blocking=False)

    def test_max_watchers_schema_validated(self):
        from keto_tpu.config import ConfigError

        with pytest.raises(ConfigError):
            Config(
                {"serve": {"read": {"grpc": {"max_watchers": 0}}}}
            )

    def test_watch_config_schema(self):
        from keto_tpu.config import ConfigError

        Config({"watch": {"poll_interval": 0.1, "buffer": 64}})
        with pytest.raises(ConfigError):
            Config({"watch": {"buffer": 0}})
        with pytest.raises(ConfigError):
            Config({"watch": {"unknown_key": 1}})

    def test_grpc_watcher_cap_exhaustion_over_wire(self):
        cfg = Config(
            {
                "dsn": "memory",
                "check": {"engine": "host"},
                "serve": {
                    "read": {"host": "127.0.0.1", "port": 0,
                             "grpc": {"host": "127.0.0.1", "port": 0,
                                      "max_watchers": 1}},
                    "write": {"host": "127.0.0.1", "port": 0},
                    "metrics": {"host": "127.0.0.1", "port": 0},
                },
                "namespaces": NAMESPACES,
            }
        )
        d = Daemon(Registry(cfg))
        d.start()
        try:
            rc1 = ReadClient(open_channel(f"127.0.0.1:{d.read_grpc_port}"))
            hub = d.registry.watch_hub()
            out1, t1 = stream_collect(rc1, 1)
            assert wait_for(
                lambda: NID in hub._states and hub._states[NID].subs
            )
            with pytest.raises(grpc.RpcError) as err:
                for _ in rc1.watch():
                    break
            assert err.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
            rc1.close()
        finally:
            d.stop()


class TestWatchMetrics:
    def test_stream_and_delivery_metrics(self, daemon, clients):
        _rc, wc = clients
        metrics = daemon.registry.metrics()
        manager = daemon.registry.relation_tuple_manager()
        base = metrics.watch_events_delivered_total._value.get()
        v0 = manager.version(nid=NID)
        wc.transact(insert=[vt(3, "metrics")])
        _ctype, events = sse_get(
            daemon.read_port,
            {"snaptoken": encode_snaptoken(v0, NID), "max_events": 1},
        )
        assert len(events) == 1
        assert metrics.watch_events_delivered_total._value.get() > base
        export = metrics.export().decode()
        for name in (
            "keto_tpu_watch_streams_active",
            "keto_tpu_watch_events_delivered_total",
            "keto_tpu_watch_resets_total",
            "keto_tpu_watch_lag_seconds",
        ):
            assert name in export


# -- satellite: REST reverse-read snaptoken parity ----------------------------


def http_get(port, path):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            raw = r.read()
            return r.status, json.loads(raw) if raw else None, dict(r.headers)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None, dict(e.headers)


class TestReverseRestSnaptokenParity:
    """The reverse-read REST routes carry the same snaptoken contract as
    _check/_check_batch: enforce the query param, return the evaluated
    version's token in X-Keto-Snaptoken."""

    def test_list_objects_header_and_enforcement(self, daemon, clients):
        _rc, wc = clients
        wc.transact(insert=[vt(1, "revparity")])
        manager = daemon.registry.relation_tuple_manager()
        current = manager.version(nid=NID)
        status, body, headers = http_get(
            daemon.read_port,
            "/relation-tuples/list-objects?namespace=videos&relation=owner"
            "&subject_id=revparity",
        )
        assert status == 200
        assert body["objects"] == ["v1"]
        assert parse_snaptoken(headers["X-Keto-Snaptoken"], NID) >= current
        # a satisfied token passes
        status, _body, _headers = http_get(
            daemon.read_port,
            "/relation-tuples/list-objects?namespace=videos&relation=owner"
            f"&subject_id=revparity&snaptoken={encode_snaptoken(current, NID)}",
        )
        assert status == 200
        # an ahead token is a 409, like check
        status, body, _headers = http_get(
            daemon.read_port,
            "/relation-tuples/list-objects?namespace=videos&relation=owner"
            f"&subject_id=revparity&snaptoken={encode_snaptoken(10**9, NID)}",
        )
        assert status == 409
        assert body["error"]["code"] == 409

    def test_list_subjects_header_and_enforcement(self, daemon, clients):
        _rc, wc = clients
        wc.transact(insert=[vt(2, "revparity2")])
        manager = daemon.registry.relation_tuple_manager()
        current = manager.version(nid=NID)
        status, body, headers = http_get(
            daemon.read_port,
            "/relation-tuples/list-subjects?namespace=videos&object=v2"
            "&relation=owner",
        )
        assert status == 200
        assert "revparity2" in body["subject_ids"]
        assert parse_snaptoken(headers["X-Keto-Snaptoken"], NID) >= current
        status, _body, _headers = http_get(
            daemon.read_port,
            "/relation-tuples/list-subjects?namespace=videos&object=v2"
            f"&relation=owner&snaptoken={encode_snaptoken(10**9, NID)}",
        )
        assert status == 409

    def test_grpc_and_client_pass_through(self, daemon, clients):
        rc, wc = clients
        wc.transact(insert=[vt(3, "revparity3")])
        manager = daemon.registry.relation_tuple_manager()
        current = manager.version(nid=NID)
        objects, _next, token = rc.list_objects(
            "videos", "owner", "revparity3",
            snaptoken=encode_snaptoken(current, NID),
        )
        assert objects == ["v3"]
        assert parse_snaptoken(token, NID) >= current
        with pytest.raises(grpc.RpcError) as err:
            rc.list_objects(
                "videos", "owner", "revparity3",
                snaptoken=encode_snaptoken(10**9, NID),
            )
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION


class TestRestartResume:
    """Watch cursor resume ACROSS A PROCESS RESTART on a file-backed
    store (the crash-recovery plane's watch contract, driven at scale by
    tools/crash_smoke.py): the pre-restart hub and subscription objects
    are gone, only the durable sqlite changelog and the client's
    snaptoken survive — the resumed cursor must still see every change
    strictly after it, exactly once, in version order."""

    def _registry(self, path):
        cfg = Config({
            "dsn": f"sqlite://{path}",
            "check": {"engine": "host"},
            "namespaces": NAMESPACES,
            "watch": {"poll_interval": 0.05},
        })
        return Registry(cfg)

    def test_hub_resume_across_reopen(self, tmp_path):
        path = str(tmp_path / "store.sqlite")
        # "process 1": write, watch, consume a prefix, die (no clean
        # hub shutdown — the subscription is simply abandoned)
        reg1 = self._registry(path)
        m1 = reg1.relation_tuple_manager()
        hub1 = reg1.watch_hub()
        sub = hub1.subscribe(NID)
        for i in range(4):  # four separate commits: versions 1..4
            m1.write_relation_tuples([vt(i)])
        m1.delete_relation_tuples([vt(1)])  # version 5
        consumed = drain(sub, 3)
        assert [e.version for e in consumed] == [1, 2, 3]
        cursor = consumed[-1].version
        m1.write_relation_tuples([vt(9, "late")])  # v6, never consumed
        # "die": nothing hub-side is persisted or handed over — only the
        # sqlite file survives (hub.stop() joins its tailers, so closing
        # the store right after is safe in-process; a real crash kills
        # both at once)
        hub1.stop()
        m1.close()

        # "process 2": fresh registry over the same file; resume at the
        # pre-crash cursor — versions 4..7 arrive exactly once, in order
        reg2 = self._registry(path)
        m2 = reg2.relation_tuple_manager()
        hub2 = reg2.watch_hub()
        sub2 = hub2.subscribe(NID, min_version=cursor)
        m2.write_relation_tuples([vt(10, "after-restart")])  # v7
        events = drain(sub2, 4)
        assert [e.kind for e in events] == ["change"] * 4
        assert [e.version for e in events] == [4, 5, 6, 7]
        # the whole resumed run matches the durable changelog exactly
        assert changes_of(events) == oracle_since(m2, cursor)
        hub2.stop()
        m2.close()

    def test_daemon_sse_resume_across_restart(self, tmp_path):
        path = str(tmp_path / "store.sqlite")

        def make(port=0):
            cfg = Config({
                "dsn": f"sqlite://{path}",
                "check": {"engine": "host"},
                "serve": {
                    "read": {"host": "127.0.0.1", "port": 0},
                    "write": {"host": "127.0.0.1", "port": 0},
                    "metrics": {"host": "127.0.0.1", "port": 0},
                },
                "namespaces": NAMESPACES,
                "watch": {"poll_interval": 0.05},
            })
            return Daemon(Registry(cfg))

        def sse_events(port, snaptoken, n):
            url = (
                f"http://127.0.0.1:{port}/relation-tuples/watch"
                f"?max_events={n}"
            )
            if snaptoken:
                url += "&snaptoken=" + urllib.parse.quote(snaptoken)
            out = []
            with urllib.request.urlopen(url, timeout=10) as r:
                data = []
                for raw in r:
                    line = raw.rstrip(b"\n")
                    if line.startswith(b"data:"):
                        data.append(line[5:].strip())
                    elif not line and data:
                        out.append(json.loads(b"".join(data)))
                        data = []
                        if len(out) >= n:
                            break
            return out

        d1 = make()
        d1.start()
        try:
            m = d1.registry.relation_tuple_manager()
            for i in range(3):  # three separate commits: versions 1..3
                m.write_relation_tuples([vt(i)])
            # consume the first two committed versions
            events = sse_events(
                d1.read_port, encode_snaptoken(0, NID), 2
            )
            cursor_token = events[-1]["snaptoken"]
            assert parse_snaptoken(cursor_token, NID) == 2
        finally:
            d1.stop()

        # restart: a second daemon process-equivalent over the same file
        d2 = make()
        d2.start()
        try:
            m2 = d2.registry.relation_tuple_manager()
            m2.write_relation_tuples([vt(7, "post-restart")])
            events = sse_events(d2.read_port, cursor_token, 2)
            versions = [parse_snaptoken(e["snaptoken"], NID) for e in events]
            assert versions == [3, 4]
            assert all(e["event_type"] == "change" for e in events)
            # exactly-once: nothing at or before the cursor re-delivered
            assert all(v > 2 for v in versions)
        finally:
            d2.stop()
