"""TPU primitive microbench for the round-3 kernel rewrite.

The round-2 TPU profile (BENCH_TPU_r03_first.json + profile_kernel.py)
shows dedupe 82x and expand 6.7x slower than CPU; both phases are
scatter-heavy. This measures every candidate replacement primitive at
kernel-realistic shapes so the rewrite is driven by numbers, not the
cost model (VERDICT r2 "Next round" item 1).

Run:  python tools/microbench3.py [--platform cpu]
Prints one JSON line per primitive: {"prim", "ms", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", choices=("auto", "cpu"), default="auto")
    ap.add_argument("--F", type=int, default=8192, help="frontier length")
    ap.add_argument("--B", type=int, default=4096, help="batch (ctx count)")
    args = ap.parse_args()
    if args.platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    import jax.numpy as jnp
    import numpy as np

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    F, B = args.F, args.B
    G = 3 * F  # candidate count after expansion (pre-dedupe), S=3 slots
    CAP = 1 << (2 * G - 1).bit_length()  # dedupe bucket table

    rng = np.random.default_rng(0)
    idx_F_B = jnp.asarray(rng.integers(0, B, F), jnp.int32)
    idx_G_CAP = jnp.asarray(rng.integers(0, CAP, G), jnp.int32)
    idx_G_F = jnp.asarray(rng.integers(0, F, G), jnp.int32)
    vals_F = jnp.asarray(rng.integers(0, 2, F), jnp.int32)
    vals_G = jnp.asarray(rng.integers(0, 1 << 20, G), jnp.uint32)
    rows_G = jnp.asarray(rng.integers(0, 1 << 20, (G, 8)), jnp.int32)
    bool_F = jnp.asarray(rng.integers(0, 2, F) == 1)
    keys_G = jnp.asarray(rng.integers(0, 1 << 30, G), jnp.uint32)
    payload_G = jnp.asarray(rng.integers(0, 1 << 30, (G,)), jnp.int32)
    table_1d = jnp.asarray(rng.integers(0, 1 << 20, CAP), jnp.int32)
    sorted_tab = jnp.asarray(np.sort(rng.integers(0, 1 << 30, G)), jnp.int32)
    q_F = jnp.asarray(rng.integers(0, 1 << 30, F), jnp.int32)

    def timed(name, fn, *xs, n=30, **extra):
        f = jax.jit(fn)
        out = f(*xs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(n):
            out = f(*xs)
        jax.block_until_ready(out)
        ms = (time.perf_counter() - t0) / n * 1e3
        print(json.dumps({"prim": name, "ms": round(ms, 4), **extra}))

    # --- scatters (the round-2 design) -----------------------------------
    timed("scatter_set_1d_G_to_F", lambda d, v: jnp.zeros(F, jnp.int32).at[d].set(v, mode="drop"),
          idx_G_F, payload_G, G=G)
    timed("scatter_set_rows_G_to_F8",
          lambda d, v: jnp.zeros((F, 8), jnp.int32).at[d].set(v, mode="drop"),
          idx_G_F, rows_G, G=G)
    timed("scatter_max_G_to_CAP",
          lambda d, v: jnp.zeros(CAP, jnp.uint32).at[d].max(v, mode="drop"),
          idx_G_CAP, vals_G, CAP=CAP)
    timed("scatter_max_F_to_B",
          lambda d, v: jnp.zeros(B, jnp.int32).at[d].max(v, mode="drop"),
          idx_F_B, vals_F)

    # --- one-hot matmul segment reductions (MXU path) --------------------
    def seg_or_matmul(seg, v):
        onehot = (seg[:, None] == jnp.arange(B, dtype=jnp.int32)[None, :])
        return (v.astype(jnp.float32) @ onehot.astype(jnp.float32)) > 0

    timed("segOR_onehot_matmul_F_B", seg_or_matmul, idx_F_B, bool_F)

    def seg_or_matmul_bf16(seg, v):
        onehot = (seg[:, None] == jnp.arange(B, dtype=jnp.int32)[None, :])
        return (v.astype(jnp.bfloat16) @ onehot.astype(jnp.bfloat16)) > 0

    timed("segOR_onehot_bf16_F_B", seg_or_matmul_bf16, idx_F_B, bool_F)

    def seg_max_fused(seg, v):
        onehot = seg[:, None] == jnp.arange(B, dtype=jnp.int32)[None, :]
        return jnp.max(jnp.where(onehot, v[:, None], 0), axis=0)

    timed("segMAX_fused_F_B", seg_max_fused, idx_F_B, vals_F)

    # --- sort-based dedupe candidates ------------------------------------
    timed("sort_1key_G", lambda k: jax.lax.sort(k), keys_G, G=G)
    timed("sort_2key_payload_G",
          lambda k, p, v: jax.lax.sort((k, p, v), num_keys=2),
          keys_G, vals_G, payload_G, G=G)

    # --- misc loop machinery ---------------------------------------------
    timed("cumsum_G", lambda v: jnp.cumsum(v), payload_G, G=G)
    timed("searchsorted_F_in_G", lambda t, q: jnp.searchsorted(t, q), sorted_tab, q_F)
    timed("gather_1d_G_from_CAP", lambda t, i: t[i], table_1d, idx_G_CAP, G=G)
    timed("gather_rows_F_P8_from_32k",
          lambda t, i: t[i],
          jnp.asarray(rng.integers(0, 1 << 20, (32768, 8)), jnp.int32),
          jnp.asarray(rng.integers(0, 32768, (F, 8)), jnp.int32))
    timed("repeat_F_S", lambda q: jnp.repeat(q, 3, total_repeat_length=3 * F), q_F)

    def wl(x):
        def body(c):
            i, y = c
            return i + 1, y * 2 - y
        return jax.lax.while_loop(lambda c: c[0] < 13, body, (0, x))

    timed("while_loop_13_trivial", wl, vals_F)

    print(json.dumps({"prim": "device", "name": str(jax.devices()[0])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
