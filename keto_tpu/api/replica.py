"""Multi-replica serving plane: N serve workers over one device engine.

The host serving plane — one transport/cache/batcher stack in one Python
process — is the structural ceiling on served throughput (BENCH_r07_cpu:
served_vs_echo_ceiling 0.711; BENCH_TPU_r04: 0.091 through the tunnel)
while the engine underneath sustains 86-158k checks/s. This module fans
the serve plane into a REPLICA GROUP: `serve.check.workers` ServeWorkers
that each run the full transport/cache/batcher stack (own gRPC server,
own REST listener, own mux accept loop, own CheckBatcher, own
CheckCache) but share ONE device engine through the existing batch
submit path — the GraphBLAS-style engine stays singular; this is purely
host-plane parallelism (ROADMAP item 1).

Replica-local state is kept consistent the Zanzibar way (PAPER.md §2.4):

  - Each worker TAILS the Watch changelog (the PR 2 hub) through a
    per-nid subscription: every committed store version advances the
    worker's `applied` version and drives its own check cache's precise
    invalidation — the same feed any out-of-process replica would ride.
  - SNAPTOKENS GATE ROUTING exactly as they already gate the PR 4
    cache: a request carrying a snaptoken newer than the worker's
    applied version is (1) HELD for catch-up within a slice of its
    deadline budget (`serve.check.replica_catchup_ms`), then (2)
    ROUTED to a fresh worker (one whose applied version satisfies the
    token — the in-process proxy: the check executes through that
    worker's cache and batcher), and only if NO worker is fresh (3)
    ESCALATED to the live store version (the shared engine always
    evaluates at the latest store state, so the answer is fresh; a
    token ahead of the store itself still 409s). A request is NEVER
    answered staler than its token demands.
  - The response snaptoken is minted from the ANSWERING worker's
    version: bounded staleness with read-your-writes, the zookie
    contract.

On top of the group, REQUEST HEDGING (Zanzibar §2.4.1/§4 — the one
latency-tolerance mechanism PR 5 explicitly could not claim because a
single-process plane has "no replica to hedge to"): a check that has not
answered within a configurable latency quantile of recent checks fires
ONE duplicate onto another worker's batcher; first answer wins, the
loser's pending is cancelled (a cancelled pending never occupies a
device batch slot). Hedges ride the PR 5 Deadline machinery — the
duplicate carries the caller's deadline, so it can never outlive the
budget, and a budget too thin to fit a hedge never fires one. Idempotent
reads only (Check; writes never hedge). Both rides' flight-recorder
launch ids land on the caller's RequestTrace, so a hedged request's two
device rides are correlatable in `GET /admin/flightrec` and the request
log.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import FIRST_COMPLETED
from concurrent.futures import TimeoutError as FutureTimeoutError
from concurrent.futures import wait as futures_wait
from typing import Optional

from ..engine.snaptoken import parse_snaptoken, require_version
from ..errors import DeadlineExceededError, OverloadedError
from ..observability import RequestTrace
from .check_cache import _fastpath_begin, require_answer_floor

# catch-up hold default: long enough for the in-process push-driven tail
# (microseconds normally), short enough that a genuinely stalled worker
# routes instead of burning the caller's budget
DEFAULT_CATCHUP_MS = 50.0


class ReplicaView:
    """One worker's replica-local applied-version view.

    A per-nid tailer thread subscribes to the WatchHub at the current
    store version and advances `applied[nid]` one committed version at a
    time, poking the worker's check cache's precise invalidation on the
    way (the cache's own changelog pass stays the source of truth for
    WHICH entries die; the tail is the wakeup any out-of-process replica
    would also have). `hold()` freezes application — the forced-lag
    test/fault hook: a held view stops advancing, so snaptoken routing
    must carry reads elsewhere."""

    def __init__(self, hub, manager, cache=None, metrics_gauge=None):
        self._hub = hub
        self._manager = manager
        self._cache = cache
        self._gauge = metrics_gauge  # per-worker applied-version gauge child
        self._cond = threading.Condition()
        self._applied: dict[str, int] = {}
        self._subs: dict[str, object] = {}
        self._hold = threading.Event()
        self._closed = False

    # -- hot path --------------------------------------------------------------

    def applied_version(self, nid: str) -> int:
        """The worker's applied store version for `nid` (lazily attaching
        the tailer on first touch). Lock-free dict read on the hot path —
        updates publish under the condition, reads ride the GIL."""
        v = self._applied.get(nid)
        if v is not None:
            return v
        return self._attach(nid)

    def catch_up(self, nid: str, min_version: int, timeout_s: float) -> int:
        """Hold the request for catch-up: wait until `applied[nid]`
        reaches `min_version` or the budget slice runs out; returns the
        applied version either way (the caller routes on a miss)."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        with self._cond:
            while self._applied.get(nid, 0) < min_version:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(remaining)
            return self._applied.get(nid, 0)

    # -- lifecycle -------------------------------------------------------------

    def _attach(self, nid: str) -> int:
        # store read + hub subscribe OUTSIDE the condition (lock
        # discipline: no store calls under held locks), then publish
        current = self._manager.version(nid=nid)
        sub = self._hub.subscribe(nid, min_version=current)
        with self._cond:
            if nid in self._applied:  # lost the attach race: keep the winner
                existing = self._applied[nid]
                late = sub
                sub = None
            else:
                self._applied[nid] = current
                self._subs[nid] = sub
                existing = None
                late = None
        if late is not None:
            late.close()
            return existing
        t = threading.Thread(
            target=self._tail_loop, args=(nid, sub),
            name=f"keto-replica-tail-{nid}", daemon=True,
        )
        t.start()
        if self._gauge is not None:
            self._gauge.set(current)
        return current

    def _tail_loop(self, nid: str, sub) -> None:
        while not self._closed:
            event = sub.get(timeout=1.0)
            if event is None:
                if sub.closed:
                    return
                continue
            # forced-lag hook: a held view buffers in the subscription
            # ring instead of applying (exactly what a wedged replica
            # tail looks like from the routing rule's perspective)
            while self._hold.is_set() and not self._closed:
                self._hold_wait()
            if self._closed:
                return
            version = event.version
            if event.is_reset:
                # unrecoverable gap (overflow/trim/bulk load): resync to
                # the reset's version and let the cache's invalidation
                # pass take its conservative whole-nid path
                version = max(version, self._applied.get(nid, 0))
            with self._cond:
                if version > self._applied.get(nid, 0):
                    self._applied[nid] = version
                self._cond.notify_all()
            if self._gauge is not None:
                self._gauge.set(version)
            if self._cache is not None:
                self._cache.notify_commit(nid)

    def _hold_wait(self) -> None:
        # tiny poll so close() and release interleave promptly; only runs
        # while the TEST/fault hold hook is set, never on the live path
        time.sleep(0.005)

    def hold(self) -> None:
        """Freeze version application (forced-lag test/fault hook)."""
        self._hold.set()

    def release(self) -> None:
        self._hold.clear()
        with self._cond:
            self._cond.notify_all()

    def close(self) -> None:
        self._closed = True
        self._hold.clear()
        with self._cond:
            subs = list(self._subs.values())
            self._subs.clear()
            self._cond.notify_all()
        for sub in subs:
            sub.close()


class HedgePolicy:
    """Deadline-budget-aware hedge trigger.

    Tracks a bounded window of recent primary-ride latencies; a hedge
    fires after the configured QUANTILE of that window (never below the
    `min_delay_ms` floor). Budget rule (the PR 5 Deadline machinery): a
    request with a deadline hedges only while at least 2x the hedge
    delay remains — a duplicate that could not finish inside the budget
    is never launched, and the duplicate itself carries the caller's
    deadline so the batchers' expiry boundaries bound it end to end."""

    WARMUP = 16  # no quantile before this many observed rides

    def __init__(self, enabled: bool = True, quantile: float = 0.95,
                 min_delay_ms: float = 1.0, window: int = 512):
        self.enabled = bool(enabled)
        self.quantile = min(max(float(quantile), 0.5), 0.999)
        self.min_delay_s = max(float(min_delay_ms), 0.0) / 1e3
        self._lat: "collections.deque[float]" = collections.deque(
            maxlen=max(int(window), HedgePolicy.WARMUP)
        )
        self._mu = threading.Lock()

    def observe(self, seconds: float) -> None:
        with self._mu:
            self._lat.append(seconds)

    def delay_s(self) -> Optional[float]:
        """Seconds to wait on the primary ride before hedging, or None
        while disabled/warming (no hedge)."""
        if not self.enabled:
            return None
        with self._mu:
            n = len(self._lat)
            if n < self.WARMUP:
                return None
            s = sorted(self._lat)
        idx = min(int(self.quantile * (n - 1) + 0.5), n - 1)
        return max(s[idx], self.min_delay_s)

    def hedge_after_s(self, deadline) -> Optional[float]:
        """The budget-gated trigger: the quantile delay, or None when
        hedging is off, still warming, or the remaining budget cannot
        fit a duplicate (< 2x the delay)."""
        delay = self.delay_s()
        if delay is None:
            return None
        if deadline is not None and deadline.remaining_s() < 2.0 * delay:
            return None
        return delay


class ServeWorker:
    """One replica: its own batcher + cache + replica view; transports
    built by the daemon carry a reference back here."""

    def __init__(self, worker_id: int, registry, batcher, cache, view,
                 group: "ReplicaGroup"):
        self.worker_id = worker_id
        self.registry = registry
        self.batcher = batcher
        self.cache = cache  # per-worker CheckCache | None (replica-local)
        self.view = view
        self.group = group
        metrics = registry.metrics()
        self._checks_counter = (
            metrics.worker_checks_total.labels(str(worker_id))
            if metrics is not None else None
        )
        # plain-int twin of worker_checks_total: the public per-worker
        # answered-check count (bench breakdown, /admin/replicas) — no
        # reaching into prometheus_client internals
        self.checks_answered = 0
        # per-worker listener ports, filled in by the daemon (observable
        # at GET /admin/replicas; tests address one replica directly)
        self.ports: dict[str, int] = {}

    def count_check(self) -> None:
        self.checks_answered += 1
        if self._checks_counter is not None:
            self._checks_counter.inc()

    def stats(self) -> dict:
        with self.batcher._pending_mu:
            pending = self.batcher._pending
        return {
            "worker": self.worker_id,
            "applied": dict(self.view._applied),
            "pending": pending,
            "checks_answered": self.checks_answered,
            "cache_entries": (
                len(self.cache._entries) if self.cache is not None else 0
            ),
            "ports": dict(self.ports),
        }


class ReplicaGroup:
    """The worker set plus the shared routing/hedging machinery."""

    def __init__(self, registry, n_workers: int, make_batcher, make_cache):
        self.registry = registry
        self.metrics = registry.metrics()
        cfg = registry.config
        self.catchup_s = float(
            cfg.get("serve.check.replica_catchup_ms", DEFAULT_CATCHUP_MS)
        ) / 1e3
        self.hedge = HedgePolicy(
            enabled=bool(cfg.get("serve.check.hedge.enabled", True)),
            quantile=float(cfg.get("serve.check.hedge.quantile", 0.95)),
            min_delay_ms=float(cfg.get("serve.check.hedge.min_delay_ms", 1.0)),
        )
        hub = registry.watch_hub()
        manager = registry.relation_tuple_manager()
        self.workers: list[ServeWorker] = []
        for i in range(n_workers):
            cache = make_cache()
            gauge = (
                self.metrics.replica_applied_version.labels(str(i))
                if self.metrics is not None else None
            )
            view = ReplicaView(hub, manager, cache=cache, metrics_gauge=gauge)
            batcher = make_batcher(self)
            self.workers.append(
                ServeWorker(i, registry, batcher, cache, view, self)
            )
        self._route_rr = 0  # fresh-worker rotation (no lock: approximate)
        self._routed = {
            outcome: self.metrics.replica_routed_total.labels(outcome)
            for outcome in ("caught_up", "routed", "escalated")
        } if self.metrics is not None else None

    # -- group state -----------------------------------------------------------

    def group_pending(self) -> int:
        """Admitted-but-unresolved checks across EVERY worker's batcher —
        the Retry-After drain estimate's numerator (a shed request cares
        how loaded the GROUP is, not one worker's queue)."""
        total = 0
        for w in self.workers:
            with w.batcher._pending_mu:
                total += w.batcher._pending
        return total

    def idle(self) -> bool:
        return all(w.batcher.idle() for w in self.workers)

    def _count_route(self, outcome: str) -> None:
        if self._routed is not None:
            self._routed[outcome].inc()

    def fresh_worker(self, nid: str, min_version: int,
                     exclude: ServeWorker) -> Optional[ServeWorker]:
        """A worker (not `exclude`) whose applied version satisfies the
        token, rotating the start index so routed load spreads."""
        n = len(self.workers)
        start = self._route_rr = (self._route_rr + 1) % max(n, 1)
        for k in range(n):
            w = self.workers[(start + k) % n]
            if w is exclude:
                continue
            if w.view.applied_version(nid) >= min_version:
                return w
        return None

    def hedge_worker(self, exclude: ServeWorker) -> Optional[ServeWorker]:
        """The next worker (round-robin) to carry a hedge duplicate."""
        n = len(self.workers)
        if n < 2:
            return None
        start = self._route_rr = (self._route_rr + 1) % n
        for k in range(n):
            w = self.workers[(start + k) % n]
            if w is not exclude:
                return w
        return None

    def stats(self) -> dict:
        return {
            "workers": [w.stats() for w in self.workers],
            "group_pending": self.group_pending(),
            "hedge": {
                "enabled": self.hedge.enabled,
                "quantile": self.hedge.quantile,
                "min_delay_ms": self.hedge.min_delay_s * 1e3,
                "delay_ms": (
                    None if self.hedge.delay_s() is None
                    else round(self.hedge.delay_s() * 1e3, 3)
                ),
            },
        }

    def close(self) -> None:
        for w in self.workers:
            w.view.close()
            if w.cache is not None:
                w.cache.close()


# -- the replica serve path ----------------------------------------------------


def resolve_version(group: ReplicaGroup, worker: ServeWorker, nid: str,
                     token: str, rt) -> tuple[ServeWorker, int]:
    """The snaptoken routing rule. Returns (answering worker, version the
    answer/response token is minted at). Raises
    SnaptokenUnsatisfiableError (409) only when the token is ahead of
    the STORE itself — replica lag alone never 409s, it routes."""
    target, version = _resolve_version(group, worker, nid, token, rt)
    if rt is not None:
        # the store-outage no-time-travel floor (same stamp as
        # enforce_snaptoken): a degraded mirror answer below the minted
        # version must 503, never serve
        rt.min_version = version
    return target, version


def _resolve_version(group: ReplicaGroup, worker: ServeWorker, nid: str,
                     token: str, rt) -> tuple[ServeWorker, int]:
    min_v = parse_snaptoken(token, nid)
    local = worker.view.applied_version(nid)
    if min_v is None or min_v <= local:
        return worker, local
    # hold for catch-up within a slice of the deadline budget (half the
    # remaining budget, capped by the configured catch-up window): the
    # in-process tail applies pushed commits in microseconds, so this is
    # the common read-your-writes path
    budget = group.catchup_s
    deadline = getattr(rt, "deadline", None) if rt is not None else None
    if deadline is not None:
        budget = min(budget, deadline.remaining_s() * 0.5)
    local = worker.view.catch_up(nid, min_v, budget)
    if local >= min_v:
        group._count_route("caught_up")
        return worker, local
    fresh = group.fresh_worker(nid, min_v, exclude=worker)
    if fresh is not None:
        group._count_route("routed")
        return fresh, fresh.view.applied_version(nid)
    # every worker is behind the token: escalate to the live store
    # version — the shared engine always evaluates at the latest store
    # state, so the answer is fresh; a token ahead of the store itself
    # is the existing 409 contract
    current = group.registry.relation_tuple_manager().version(nid=nid)
    require_version(current, min_v)
    group._count_route("escalated")
    return worker, current


def _wait_result(batcher, pending, rt):
    """CheckBatcher.wait_pending with the hedge policy's latency feed."""
    return batcher.wait_pending(pending, rt)


def _hedged_ride(group: ReplicaGroup, worker: ServeWorker, t, max_depth: int,
                 nid, rt):
    """One check through `worker`'s batcher with deadline-budget-aware
    hedging: if the primary ride has not answered within the hedge
    policy's quantile delay, fire ONE duplicate onto another worker's
    batcher; first answer wins, the loser's pending is cancelled (a
    cancelled pending never occupies a device batch slot — the batchers
    skip done futures at their expiry boundary). Returns
    (CheckResult, covered_version | None) like check_versioned."""
    metrics = group.metrics
    deadline = getattr(rt, "deadline", None) if rt is not None else None
    t0 = time.perf_counter()
    primary = worker.batcher.submit(t, max_depth, nid=nid, rt=rt)
    hedge_after = group.hedge.hedge_after_s(deadline)
    if hedge_after is not None and deadline is not None:
        hedge_after = min(hedge_after, max(deadline.remaining_s(), 1e-4))
    if hedge_after is None:
        out = _wait_result(worker.batcher, primary, rt)
        group.hedge.observe(time.perf_counter() - t0)
        return out
    try:
        out = primary.future.result(timeout=hedge_after)
        group.hedge.observe(time.perf_counter() - t0)
        return out
    except FutureTimeoutError:
        pass
    other = group.hedge_worker(exclude=worker)
    if other is None:
        out = _wait_result(worker.batcher, primary, rt)
        group.hedge.observe(time.perf_counter() - t0)
        return out
    # the duplicate carries its own RequestTrace (child span, SAME
    # deadline): its launch ids accumulate separately, then merge onto
    # the caller's trace so the request log shows both rides
    hedge_rt = RequestTrace(
        rt.ctx.child() if rt is not None and rt.ctx is not None else None,
        deadline=deadline,
    )
    try:
        hedge = other.batcher.submit(t, max_depth, nid=nid, rt=hedge_rt)
    except OverloadedError:
        # the hedge target's queue is full or its batcher is draining:
        # hedging is a pure latency optimization, so a failed duplicate
        # must never fail the request — the healthy primary ride wins
        out = _wait_result(worker.batcher, primary, rt)
        group.hedge.observe(time.perf_counter() - t0)
        return out
    if metrics is not None:
        metrics.hedge_launched_total.inc()
    remaining = None
    if deadline is not None:
        remaining = max(deadline.remaining_s(), 1e-4)
    done, _ = futures_wait(
        {primary.future, hedge.future},
        timeout=remaining, return_when=FIRST_COMPLETED,
    )
    try:
        if not done:
            # neither ride answered inside the budget: the typed 504,
            # counted once (both pendings marked so the collectors'
            # queue-drop never double-counts)
            primary.dl_counted = hedge.dl_counted = True
            if metrics is not None:
                metrics.deadline_exceeded_total.labels("wait").inc()
            raise DeadlineExceededError(
                "request deadline expired waiting for the check batch"
            )
        winner = primary if primary.future in done else hedge
        loser = hedge if winner is primary else primary
        if loser.future.cancel() and metrics is not None:
            metrics.hedge_cancelled_total.inc()
        if metrics is not None:
            metrics.hedge_wins_total.labels(
                "primary" if winner is primary else "hedge"
            ).inc()
        group.hedge.observe(time.perf_counter() - t0)
        return winner.future.result()
    finally:
        # flight-recorder correlation: the hedge ride's launch ids join
        # the caller's trace whatever the outcome
        if rt is not None and hedge_rt.launch_ids:
            rt.launch_ids.extend(hedge_rt.launch_ids)


def serve_on(worker: ServeWorker, nid: str, t, max_depth: int, version: int,
              rt, hedged: bool = True):
    """The per-worker serve fast path (cache -> batcher -> store), the
    replica twin of check_cache.cached_check. `version` is the version
    the answer must be authoritative at (the worker's applied version or
    the escalated store version)."""
    cache = worker.cache
    res, gen = _fastpath_begin(cache, nid, t, max_depth, version, rt)
    if res is not None:
        worker.count_check()
        return res
    if hedged:
        res, computed_v = _hedged_ride(
            worker.group, worker, t, max_depth, nid, rt
        )
    else:
        res, computed_v = worker.batcher.check_versioned(
            t, max_depth, nid=nid, rt=rt
        )
    require_answer_floor(computed_v, version)
    if cache is not None:
        cache.store(nid, t, max_depth, res, computed_v, version, gen=gen)
    worker.count_check()
    return res


def replica_check(worker: ServeWorker, nid: str, t, max_depth: int,
                  token: str, rt):
    """The transports' replica-mode check path: snaptoken routing, then
    the answering worker's cache/batcher with hedging. Returns
    (CheckResult, version) — the version mints the response snaptoken."""
    group = worker.group
    target, version = resolve_version(group, worker, nid, token, rt)
    res = serve_on(target, nid, t, max_depth, version, rt)
    return res, version


async def replica_check_async(worker: ServeWorker, aio_batcher, nid: str, t,
                              max_depth: int, token: str, rt, loop,
                              executor):
    """The aio plane's replica check: the same routing rule; the fast
    path (applied version already satisfies the token) stays entirely
    in-loop — version read and cache lookup are dict operations. The
    slow paths (catch-up hold, routing to another worker's threaded
    stack) run on the executor. Hedging rides the threaded plane only:
    an aio check that routes executes on the target worker's threaded
    batcher (which hedges); an unrouted one rides this listener's own
    in-loop batcher unhedged — cross-loop duplicate cancellation is not
    worth the loop hops for the listener that already has no handoffs."""
    group = worker.group
    min_v = parse_snaptoken(token, nid)
    local = worker.view.applied_version(nid)
    if min_v is None or min_v <= local:
        version = local
        cache = worker.cache
        res, gen = _fastpath_begin(cache, nid, t, max_depth, version, rt)
        if res is not None:
            worker.count_check()
            return res, version
        res, computed_v = await aio_batcher.check_versioned(
            t, max_depth, nid=nid, rt=rt
        )
        require_answer_floor(computed_v, version)
        if cache is not None:
            cache.store(nid, t, max_depth, res, computed_v, version, gen=gen)
        worker.count_check()
        return res, version
    # behind the token: hold/route/escalate off-loop (condition waits and
    # store reads must not block the event loop)
    return await loop.run_in_executor(
        executor,
        lambda: replica_check(worker, nid, t, max_depth, token, rt),
    )
