"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The container's sitecustomize registers the axon TPU PJRT plugin at
interpreter startup and force-selects it via
jax.config.update("jax_platforms", "axon,cpu"), overriding the
JAX_PLATFORMS env var; initializing that backend blocks on the TPU
tunnel. Tests must run on host CPU with 8 virtual devices, so we set the
XLA flags before any backend is created and flip the platform config
back to cpu. Benches (bench.py) run outside pytest and keep the real TPU.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (must come after the env setup above)
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: runs on the real TPU backend (subprocess; skipped unless "
        "KETO_TPU_TESTS=1 and the backend is healthy)",
    )
    # KETO_LOCKWATCH=1: install the runtime lock-order / blocking-under-
    # lock detector (keto_tpu/analysis/lockwatch.py) for the whole
    # session — the `go test -race` leg. Hooks below fail the exact test
    # whose execution produced a violation, with creation-site stacks.
    from keto_tpu.analysis import lockwatch

    lockwatch.pytest_session_start()


@pytest.hookimpl(wrapper=True)
def pytest_runtest_teardown(item, nextitem):
    # wrapper: the post-yield check runs AFTER the core runner's
    # teardown_exact, i.e. after this test's fixture finalizers (daemon
    # stops, batcher closes live in finalizers) — a violation raised
    # there fails THIS test, not the next one
    yield
    from keto_tpu.analysis import lockwatch

    # the high-water mark lives on the watcher (advanced before the
    # raise), so one violation fails exactly its own test instead of
    # cascading the same report into every later test
    lockwatch.check_test(item.nodeid)


def pytest_sessionfinish(session, exitstatus):
    # backstop for violations produced after the last test's teardown
    # hook (session-scoped finalizers torn down late, atexit-adjacent
    # threads): re-check before uninstall so they can never be dropped
    from keto_tpu.analysis import lockwatch

    lockwatch.check_test("session teardown (after the last test)")


def pytest_unconfigure(config):
    from keto_tpu.analysis import lockwatch

    lockwatch.uninstall()
