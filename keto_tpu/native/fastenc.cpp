// Native ingest accelerator: sorted-unique encoding of fixed-width
// byte keys.
//
// The columnar ingest's dominant cost at 1e8 scale is
// np.unique(S-array) — a comparison sort over every row
// (n log n memcmps; measured ~500 s of the 1e8 build's 634 s encode
// phase, SCALE_1e8_BUILD_r04.json). The contract the engine needs is
// narrower than a full sort: dense ids in SORTED-unique order
// (ArrayMap's searchsorted lookups require sorted keys) plus
// first-occurrence indices. That is O(n) hash work + a sort of only
// the UNIQUES:
//
//   1. one open-addressing pass dedupes n rows into u slots
//      (FNV-1a over the row bytes; first-comer claims the slot and
//      tracks the minimum original index for the first-occurrence
//      contract),
//   2. std::sort of the u unique rows (u << n in every real dataset:
//      objects/subjects repeat across tuples),
//   3. one pass maps every row's slot to its sorted rank.
//
// Exposed as a plain C ABI for ctypes (this image has no pybind11);
// keto_tpu/native/__init__.py compiles it on demand with g++ and falls
// back to the numpy path when no compiler is available. Single
// threaded on purpose: the bench hosts are 1-core, and correctness
// must not depend on thread count.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// Chunked 8-bytes-at-a-time hash (memcpy keeps unaligned row starts
// legal; trailing bytes zero-padded into the final chunk — harmless
// because fixed-width rows already embed their \x00 padding in the
// compared bytes). Every chunk goes through a murmur3-style fmix64:
// a plain chunked FNV (one multiply per chunk) does NOT diffuse
// middle-byte differences into the table-mask bits and probe chains
// explode — measured 2.5x slower end-to-end than the byte-wise
// version before this mixer.
inline uint64_t fmix64(uint64_t x) {
    x ^= x >> 33;
    x *= 0xff51afd7ed558ccdull;
    x ^= x >> 33;
    x *= 0xc4ceb9fe1a85ec53ull;
    x ^= x >> 33;
    return x;
}

inline uint64_t hash_row(const uint8_t* p, int64_t w) {
    uint64_t h = 0x9e3779b97f4a7c15ull ^ static_cast<uint64_t>(w);
    int64_t i = 0;
    for (; i + 8 <= w; i += 8) {
        uint64_t c;
        std::memcpy(&c, p + i, 8);
        h = fmix64(h ^ c) + 0x165667b19e3779f9ull;
    }
    if (i < w) {
        uint64_t c = 0;
        std::memcpy(&c, p + i, static_cast<size_t>(w - i));
        h = fmix64(h ^ c) + 0x165667b19e3779f9ull;
    }
    return fmix64(h);
}

struct Slot {
    uint64_t h;    // full hash: probe mismatches resolve WITHOUT
                   // touching the representative row (second random
                   // access); equality still memcmp-confirms, so a
                   // 64-bit collision can never merge distinct keys
    int32_t rep;   // representative row index, -1 = empty (int32: n is
                   // guarded <= INT32_MAX, and the field is half the
                   // per-slot footprint at 1e8-row calls)
};

}  // namespace

extern "C" {

// keys: n rows of w bytes, contiguous.
// out_first_idx: int64[n] (filled for the first n_uniq entries with the
//   minimal original row index of each unique key, in sorted key
//   order — so keys[out_first_idx[:n_uniq]] IS the sorted unique set).
// out_codes: int32[n] (sorted-unique rank of every input row; identical
//   to np.searchsorted(sorted_uniques, keys)).
// Returns n_uniq, or -1 when n would overflow the int32 row/slot
// fields (slot ids reach 2n rounded up to a power of two, so n is
// capped at 2^30 ≈ 1.07e9 rows — beyond every supported table size;
// callers fall back to numpy).
int64_t keto_unique_encode(const uint8_t* keys, int64_t n, int64_t w,
                           int64_t* out_first_idx, int32_t* out_codes)
// a C++ exception escaping an extern "C" ctypes entry point calls
// std::terminate and kills the whole process; std::bad_alloc from the
// std::vector allocations (cap can reach 2n slots) must instead return
// the error sentinel so the Python wrapper falls back to numpy (which
// raises a catchable MemoryError if the host is truly out)
try {
    if (n == 0) return 0;
    if (n > (int64_t{1} << 30)) return -1;
    // power-of-two capacity at load <= 0.5
    uint64_t cap = 1;
    while (cap < static_cast<uint64_t>(2 * n)) cap <<= 1;
    const uint64_t mask = cap - 1;
    std::vector<Slot> slots(cap, Slot{0, -1});
    std::vector<int32_t> row_slot(n);

    // software-pipelined probe: hash a block, prefetch its home slots,
    // then probe — the random slot read is the dominant stall, and the
    // block gives the prefetches time to land
    constexpr int64_t BLK = 32;
    uint64_t hs[BLK];
    for (int64_t b = 0; b < n; b += BLK) {
        const int64_t e = std::min(b + BLK, n);
        for (int64_t i = b; i < e; ++i) {
            hs[i - b] = hash_row(keys + i * w, w);
            __builtin_prefetch(&slots[hs[i - b] & mask], 1, 1);
        }
        for (int64_t i = b; i < e; ++i) {
            const uint8_t* row = keys + i * w;
            const uint64_t h = hs[i - b];
            uint64_t s = h & mask;
            for (;;) {
                Slot& sl = slots[s];
                if (sl.rep < 0) {
                    sl.h = h;
                    // ascending i: rep IS the first occurrence
                    sl.rep = static_cast<int32_t>(i);
                    break;
                }
                if (sl.h == h
                    && std::memcmp(keys + static_cast<int64_t>(sl.rep) * w,
                                   row, w) == 0) {
                    break;
                }
                s = (s + 1) & mask;  // linear probe
            }
            row_slot[i] = static_cast<int32_t>(s);
        }
    }

    // collect occupied slots, sort their representative rows bytewise
    std::vector<int64_t> occupied;
    occupied.reserve(static_cast<size_t>(n));
    for (uint64_t s = 0; s < cap; ++s) {
        if (slots[s].rep >= 0) occupied.push_back(static_cast<int64_t>(s));
    }
    const int64_t n_uniq = static_cast<int64_t>(occupied.size());
    if (n_uniq > INT32_MAX) return -1;
    std::sort(occupied.begin(), occupied.end(),
              [keys, w, &slots](int64_t a, int64_t b) {
                  return std::memcmp(keys + slots[a].rep * w,
                                     keys + slots[b].rep * w, w) < 0;
              });

    // sorted rank per slot, first-occurrence per rank
    std::vector<int32_t> slot_rank(cap);
    for (int64_t r = 0; r < n_uniq; ++r) {
        const int64_t s = occupied[static_cast<size_t>(r)];
        slot_rank[static_cast<size_t>(s)] = static_cast<int32_t>(r);
        out_first_idx[r] = slots[static_cast<size_t>(s)].rep;
    }
    for (int64_t i = 0; i < n; ++i) {
        out_codes[i] = slot_rank[static_cast<size_t>(row_slot[i])];
    }
    return n_uniq;
} catch (...) {
    return -1;  // numpy fallback
}

// Round-based open-addressing table construction, bit-identical to the
// numpy builder in engine/snapshot.py (_build_hash_table): all pending
// keys probe the slot given by snapshot.probe_slot at round r — the
// BUCKETIZED sequence ((h1 + (r/8)*h2) mod cap/8)*8 + r%8, filling the
// 8 consecutive slots of a bucket before double-hash-stepping to the
// next bucket (the device kernel fetches whole bucket rows; see
// engine/kernel._bucket_rows). Among a round's contenders for a slot
// that was free at round start, the LOWEST index wins; losers advance
// one round. Iterating pending in ascending index order and claiming
// on first-empty reproduces that rule exactly — the lowest-index
// contender reaches each slot first — without the per-round argsort
// that dominates the numpy builder at 1e7+ keys (the 5e7 build notes
// measured the sort at ~25% of per-shard build).
//
// No key comparisons happen at all (duplicate keys each take a slot,
// exactly like the numpy rounds); the caller computes h1/h2 with its
// vectorized hash and pre-fills the output arrays with EMPTY.
//
// key_cols: [n_cols][n] int32, out_cols: [n_cols][cap] int32.
// Returns max_probes (>= 1), or -1 when any key needs > 64 rounds
// (pathological clustering: the caller doubles cap and retries, same
// as the numpy path).
int64_t keto_build_probe_table(const uint32_t* h1, const uint32_t* h2,
                               int64_t n, const int32_t* key_cols,
                               int64_t n_cols, const int32_t* values,
                               int32_t* out_cols, int32_t* out_vals,
                               int64_t cap, int32_t empty, int64_t spb)
try {
    if (n == 0) return 1;
    if (n > (int64_t{1} << 30)) return -2;  // int32 pending indices
    // spb = slots per bucket (snapshot.slots_per_bucket: 8 for edge
    // tables, 16 for pair tables); must be a power of two <= cap
    if (spb < 1 || (spb & (spb - 1)) != 0 || cap < spb) return -2;
    const uint32_t sh = static_cast<uint32_t>(__builtin_ctzll(
        static_cast<uint64_t>(spb)));
    const uint32_t smask = static_cast<uint32_t>(spb - 1);
    const uint32_t bmask = static_cast<uint32_t>(cap / spb - 1);
    std::vector<int32_t> pending(static_cast<size_t>(n));
    for (int64_t i = 0; i < n; ++i) pending[static_cast<size_t>(i)] =
        static_cast<int32_t>(i);
    std::vector<int32_t> lost;
    lost.reserve(pending.size());
    int64_t round = 0;
    while (!pending.empty()) {
        if (round >= 64) return -1;  // numpy path: max 64 probe rounds
        const uint32_t r = static_cast<uint32_t>(round);
        lost.clear();
        for (int32_t i : pending) {
            const uint32_t s =
                ((h1[i] + (r >> sh) * h2[i]) & bmask) * (smask + 1u)
                + (r & smask);
            if (out_vals[s] == empty) {
                out_vals[s] = values[i];
                for (int64_t c = 0; c < n_cols; ++c) {
                    out_cols[c * cap + s] = key_cols[c * n + i];
                }
            } else {
                lost.push_back(i);
            }
        }
        pending.swap(lost);
        ++round;
    }
    return round;
} catch (...) {
    return -2;  // numpy fallback (see keto_unique_encode's rationale)
}

}  // extern "C"
