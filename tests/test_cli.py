"""CLI tests: parse/validate run pure; client commands run against an
in-process daemon (the reference exercises its CLI through the cobra
executor against a live server the same way, cmd/**/*_test.go)."""

import json

import pytest

from keto_tpu.api.daemon import Daemon
from keto_tpu.cli import main
from keto_tpu.config import Config
from keto_tpu.ketoapi import RelationQuery
from keto_tpu.registry import Registry


@pytest.fixture(scope="module")
def daemon():
    cfg = Config(
        {
            "dsn": "memory",
            "check": {"engine": "host"},
            "serve": {
                "read": {"host": "127.0.0.1", "port": 0},
                "write": {"host": "127.0.0.1", "port": 0},
                "metrics": {"host": "127.0.0.1", "port": 0},
            },
            "namespaces": [
                {"name": "videos", "relations": [{"name": "owner"}, {"name": "view"}]}
            ],
        }
    )
    d = Daemon(Registry(cfg))
    d.start()
    yield d
    d.stop()


@pytest.fixture
def remotes(daemon):
    return [
        "--read-remote", f"127.0.0.1:{daemon.read_port}",
        "--write-remote", f"127.0.0.1:{daemon.write_port}",
    ]


@pytest.fixture(autouse=True)
def clean_store(daemon):
    yield
    daemon.registry.relation_tuple_manager().delete_all_relation_tuples(
        RelationQuery(), nid=daemon.registry.nid
    )


def run(capsys, argv):
    code = main(argv)
    out = capsys.readouterr()
    return code, out.out, out.err


def test_version(capsys):
    code, out, _ = run(capsys, ["version"])
    assert code == 0 and out.strip()


def test_parse_single_json(capsys):
    code, out, _ = run(
        capsys,
        ["relation-tuple", "parse", "videos:v1#owner@alice", "--format", "json"],
    )
    assert code == 0
    assert json.loads(out) == {
        "namespace": "videos",
        "object": "v1",
        "relation": "owner",
        "subject_id": "alice",
    }


def test_parse_table_and_comments(capsys, tmp_path):
    f = tmp_path / "tuples.txt"
    f.write_text("// comment\nvideos:v1#owner@alice\n\nvideos:v2#view@(videos:v2#owner)\n")
    code, out, _ = run(capsys, ["relation-tuple", "parse", str(f)])
    assert code == 0
    assert "NAMESPACE" in out and "videos:v2#owner" in out


def test_parse_invalid_exits_1(capsys):
    code, _, err = run(capsys, ["relation-tuple", "parse", "not-a-tuple"])
    assert code == 1 and err


def test_namespace_validate(capsys, tmp_path):
    good = tmp_path / "ns.json"
    good.write_text(json.dumps({"name": "files", "relations": [{"name": "owner"}]}))
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    code, out, err = run(capsys, ["namespace", "validate", str(good)])
    assert code == 0 and "OK" in out
    code, out, err = run(capsys, ["namespace", "validate", str(good), str(bad)])
    assert code == 1 and "INVALID" in err


def test_namespace_validate_opl(capsys, tmp_path):
    f = tmp_path / "ns.ts"
    f.write_text(
        "class User implements Namespace {}\n"
        "class Doc implements Namespace {\n"
        "  related: { owners: User[] }\n"
        "  permits = { view: (ctx) => this.related.owners.includes(ctx.subject) }\n"
        "}\n"
    )
    code, out, _ = run(capsys, ["namespace", "validate", str(f)])
    assert code == 0 and "Doc" in out


def test_create_check_get_expand_delete_all(capsys, tmp_path, remotes):
    t = tmp_path / "t.json"
    t.write_text(
        json.dumps(
            [
                {"namespace": "videos", "object": "v1", "relation": "owner", "subject_id": "alice"},
                {"namespace": "videos", "object": "v1", "relation": "view",
                 "subject_set": {"namespace": "videos", "object": "v1", "relation": "owner"}},
            ]
        )
    )
    code, out, _ = run(capsys, ["relation-tuple", "create", str(t), *remotes])
    assert code == 0 and "Created 2" in out

    code, out, _ = run(capsys, ["check", "alice", "view", "videos", "v1", *remotes])
    assert code == 0 and out.strip() == "Allowed"
    code, out, _ = run(capsys, ["check", "eve", "view", "videos", "v1", *remotes])
    assert code == 0 and out.strip() == "Denied"
    code, out, _ = run(
        capsys, ["check", "alice", "view", "videos", "v1", "--format", "json", *remotes]
    )
    assert json.loads(out) == {"allowed": True}

    code, out, _ = run(
        capsys, ["relation-tuple", "get", "--namespace", "videos", "--format", "json", *remotes]
    )
    assert code == 0 and len(json.loads(out)["relation_tuples"]) == 2

    code, out, _ = run(capsys, ["expand", "view", "videos", "v1", *remotes])
    assert code == 0 and "alice" in out

    code, out, err = run(
        capsys, ["relation-tuple", "delete-all", "--namespace", "videos", *remotes]
    )
    assert code == 1 and "--force" in err  # refuses without --force
    code, out, _ = run(
        capsys,
        ["relation-tuple", "delete-all", "--namespace", "videos", "--force", *remotes],
    )
    assert code == 0
    code, out, _ = run(
        capsys, ["relation-tuple", "get", "--namespace", "videos", "--format", "json", *remotes]
    )
    assert json.loads(out)["relation_tuples"] == []


def test_delete_tuples_from_file(capsys, tmp_path, remotes):
    t = tmp_path / "t.json"
    t.write_text(
        json.dumps({"namespace": "videos", "object": "v3", "relation": "owner", "subject_id": "bo"})
    )
    run(capsys, ["relation-tuple", "create", str(t), *remotes])
    code, out, _ = run(capsys, ["relation-tuple", "delete", str(t), *remotes])
    assert code == 0 and "Deleted 1" in out
    code, out, _ = run(capsys, ["check", "bo", "owner", "videos", "v3", *remotes])
    assert out.strip() == "Denied"


def test_status(capsys, remotes):
    code, out, _ = run(capsys, ["status", *remotes])
    assert code == 0 and out.strip() == "SERVING"


def test_migrate_status_and_up(capsys, tmp_path):
    cfg = tmp_path / "keto.yml"
    cfg.write_text(f"dsn: sqlite://{tmp_path}/keto.db\n")
    code, out, _ = run(capsys, ["migrate", "status", "-c", str(cfg)])
    assert code == 0 and "pending" in out.lower()
    code, out, _ = run(capsys, ["migrate", "up", "--yes", "-c", str(cfg)])
    assert code == 0
    code, out, _ = run(capsys, ["migrate", "status", "-c", str(cfg)])
    assert "pending" not in out.lower()


def test_migrate_memory_noop(capsys, tmp_path):
    cfg = tmp_path / "keto.yml"
    cfg.write_text("dsn: memory\n")
    code, out, _ = run(capsys, ["migrate", "up", "--yes", "-c", str(cfg)])
    assert code == 0 and "no migrations" in out
